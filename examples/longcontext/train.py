"""Long-context elastic training example: ring attention over a
sequence-sharded mesh.

Capability parity: the reference's long-context subsystem
(atorch/modules/distributed_transformer/distributed_attention.py:21-115 —
DistributedSelfAttention with sequence-sharded KV and distributed online
softmax). TPU re-design: `attn_impl="ring"` runs a ppermute ring of Pallas
flash-attention blocks over the `sequence` mesh axis; activations are
sharded (1/N of the sequence per device), so the trainable context length
scales linearly with the axis size while the math stays exactly equal to
single-device attention.

Run on one host over all local devices (sequence axis = device count):
    python -m dlrover_tpu.run --standalone examples/longcontext/train.py \
        --seq 32768 --seq-shards 4 --steps 50 --ckpt-dir /tmp/longctx-ckpt
Multi-node: as examples/nanogpt, one agent per host.

Everything the nanogpt example demonstrates (elastic restart, checkpoint
+ sampler resume, speed reports) applies unchanged — the loop is the same
ElasticTrainLoop; only the mesh and the attention impl differ.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def parse_args(argv=None):
    parser = argparse.ArgumentParser("longcontext-train")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--global-batch", type=int, default=2)
    parser.add_argument("--seq", type=int, default=32768)
    parser.add_argument("--seq-shards", type=int, default=0,
                        help="sequence-axis size (0 = all local devices)")
    parser.add_argument("--hidden", type=int, default=512)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--save-interval", type=int, default=20)
    parser.add_argument("--log-file", default="",
                        help="append step logs here (tests parse it)")
    return parser.parse_args(argv)


def long_batches(vocab_size, sampler, global_batch, seq):
    """Synthetic long documents: per-index seeded random walks, so a
    resumed sampler regenerates identical data."""
    batch = []
    for idx in sampler:
        rng = np.random.default_rng(idx)
        walk = np.cumsum(rng.integers(-3, 4, seq + 1)).astype(np.int32)
        batch.append(walk % vocab_size)
        if len(batch) == global_batch:
            chunk = np.stack(batch)
            batch = []
            yield chunk[:, :-1], chunk[:, 1:]


def main(argv=None) -> int:
    args = parse_args(argv)

    from dlrover_tpu.agent.elastic_agent import init_distributed

    init_distributed()

    import jax
    import optax

    from dlrover_tpu.models.llama import (
        Llama,
        LlamaConfig,
        cross_entropy_loss,
    )
    from dlrover_tpu.parallel.mesh import MeshSpec
    from dlrover_tpu.trainer.elastic_loop import (
        ElasticTrainLoop,
        TrainLoopConfig,
    )
    from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

    seq_shards = args.seq_shards or max(1, len(jax.devices()))
    if args.seq % seq_shards:
        raise SystemExit(
            f"--seq {args.seq} must divide by seq shards {seq_shards}")
    if args.hidden < 64 or args.hidden % 64:
        raise SystemExit(
            f"--hidden {args.hidden} must be a multiple of 64 "
            f"(64-dim attention heads)")
    cfg = LlamaConfig(
        vocab_size=1024, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.hidden // 64,
        num_kv_heads=args.hidden // 64,
        intermediate_size=args.hidden * 3,
        max_seq_len=args.seq, attn_impl="ring",
    )

    client = None
    if os.environ.get("DLROVER_TPU_MASTER_ADDR"):
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient.singleton()

    loop = ElasticTrainLoop(
        Llama(cfg),
        optax.adafactor(args.lr),
        cross_entropy_loss,
        TrainLoopConfig(
            global_batch=args.global_batch,
            seq_len=args.seq,
            max_steps=args.steps,
            checkpoint_dir=args.ckpt_dir,
            save_interval_steps=args.save_interval,
            report_interval_steps=10,
            mesh_spec=MeshSpec(sequence=seq_shards),
        ),
        master_client=client,
    )
    loop.install_signal_handler()

    sampler = ElasticDistributedSampler(
        dataset_size=10 ** 6, shuffle=True, seed=0)
    state, start_step = loop.restore_or_init(jax.random.PRNGKey(0),
                                             sampler)

    def log(message: str) -> None:
        print(message, flush=True)
        if args.log_file:
            with open(args.log_file, "a") as f:
                f.write(message + "\n")

    log(f"longcontext: start_step={start_step} seq={args.seq} "
        f"seq_shards={seq_shards} backend={jax.default_backend()}")
    if args.steps <= start_step:
        log("longcontext: nothing to do")
        loop.close()
        return 0

    data = long_batches(cfg.vocab_size, sampler, args.global_batch,
                        args.seq)
    loop.config.max_steps = args.steps - start_step
    state, metrics = loop.run(state, data, start_step=start_step,
                              sampler=sampler)
    final_step = int(metrics.get("step", start_step))
    log(f"longcontext: done step={final_step} "
        f"loss={metrics.get('loss', -1):.4f}")
    loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
