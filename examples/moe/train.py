"""Mixture-of-experts elastic training example: expert parallelism over
the `expert` mesh axis.

Capability parity: the reference's MoE path (atorch modules/moe —
MOELayer with expert-parallel groups injected into transformer blocks,
moe/inject.py). TPU re-design: `LlamaMoE` is a first-class model family
(Mixtral shape — Llama attention + sparse expert MLPs with capacity-based
top-k routing); expert weights carry the `expert` logical axis, so on an
expert-sharded mesh XLA places one dispatch all-to-all per MoE layer and
each device holds 1/E of the expert parameters. Router load-balancing
aux losses ride the mutable 'losses' collection and are folded into the
objective by the standard trainer — no bespoke loop.

Run on one host over all local devices (expert axis = device count):
    python -m dlrover_tpu.run --standalone examples/moe/train.py \
        --experts 4 --expert-shards 4 --steps 50 --ckpt-dir /tmp/moe-ckpt
Multi-node: as examples/nanogpt, one agent per host.

Elastic restart, checkpoint + sampler resume, and speed reports all
apply unchanged — same ElasticTrainLoop; only the mesh and model differ.
strategy="auto" on an MoE model picks the expert axis by itself (the
planner forces an expert_parallel candidate; see
tests/test_auto_accelerate.py::test_auto_on_moe_picks_expert_axis) —
this example pins it explicitly for clarity.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def parse_args(argv=None):
    parser = argparse.ArgumentParser("moe-train")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--global-batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=256)
    parser.add_argument("--experts", type=int, default=4)
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--expert-shards", type=int, default=0,
                        help="expert-axis size (0 = all local devices, "
                             "capped at --experts)")
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--save-interval", type=int, default=20)
    parser.add_argument("--log-file", default="",
                        help="append step logs here (tests parse it)")
    return parser.parse_args(argv)


def token_batches(vocab_size, sampler, global_batch, seq):
    """Synthetic documents: per-index seeded, so a resumed sampler
    regenerates identical data."""
    batch = []
    for idx in sampler:
        rng = np.random.default_rng(idx)
        batch.append(
            rng.integers(0, vocab_size, seq + 1).astype(np.int32))
        if len(batch) == global_batch:
            chunk = np.stack(batch)
            batch = []
            yield chunk[:, :-1], chunk[:, 1:]


def main(argv=None) -> int:
    args = parse_args(argv)

    from dlrover_tpu.agent.elastic_agent import init_distributed

    init_distributed()

    import jax
    import optax

    from dlrover_tpu.models.llama import cross_entropy_loss
    from dlrover_tpu.models.llama_moe import LlamaMoE, LlamaMoEConfig
    from dlrover_tpu.parallel.mesh import MeshSpec
    from dlrover_tpu.trainer.elastic_loop import (
        ElasticTrainLoop,
        TrainLoopConfig,
    )
    from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

    if args.hidden < 64 or args.hidden % 64:
        raise SystemExit(
            f"--hidden {args.hidden} must be a multiple of 64 "
            f"(64-dim attention heads)")
    if args.expert_shards:
        expert_shards = args.expert_shards
        if args.experts % expert_shards:
            raise SystemExit(
                f"--experts {args.experts} must divide by expert "
                f"shards {expert_shards}")
    else:
        # auto: the largest device count that divides the expert count
        # (the analyser's own sizing policy, auto/engine/analyser.py)
        n_dev = max(1, len(jax.devices()))
        expert_shards = max(
            d for d in range(1, n_dev + 1) if args.experts % d == 0)
    cfg = LlamaMoEConfig(
        vocab_size=1024, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.hidden // 64,
        num_kv_heads=args.hidden // 64,
        intermediate_size=args.hidden * 2,
        max_seq_len=args.seq,
        num_experts=args.experts, top_k=args.top_k,
        attn_impl="flash" if jax.default_backend() == "tpu"
        else "reference",
    )

    client = None
    if os.environ.get("DLROVER_TPU_MASTER_ADDR"):
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient.singleton()

    loop = ElasticTrainLoop(
        # deterministic=False = TRAINING routing semantics (train
        # capacity factor + router jitter when configured); the trainer
        # supplies the per-step gating rng stream
        LlamaMoE(cfg, deterministic=False),
        optax.adafactor(args.lr),
        cross_entropy_loss,
        TrainLoopConfig(
            global_batch=args.global_batch,
            seq_len=args.seq,
            max_steps=args.steps,
            checkpoint_dir=args.ckpt_dir,
            save_interval_steps=args.save_interval,
            report_interval_steps=10,
            mesh_spec=MeshSpec(expert=expert_shards),
        ),
        master_client=client,
    )
    loop.install_signal_handler()

    sampler = ElasticDistributedSampler(
        dataset_size=10 ** 6, shuffle=True, seed=0)
    state, start_step = loop.restore_or_init(jax.random.PRNGKey(0),
                                             sampler)

    def log(message: str) -> None:
        print(message, flush=True)
        if args.log_file:
            with open(args.log_file, "a") as f:
                f.write(message + "\n")

    active = cfg.active_param_count() / 1e6
    total = cfg.param_count() / 1e6
    log(f"moe: start_step={start_step} experts={args.experts} "
        f"expert_shards={expert_shards} params={total:.1f}M "
        f"active={active:.1f}M backend={jax.default_backend()}")
    if args.steps <= start_step:
        log("moe: nothing to do")
        loop.close()
        return 0

    data = token_batches(cfg.vocab_size, sampler, args.global_batch,
                         args.seq)
    loop.config.max_steps = args.steps - start_step
    state, metrics = loop.run(state, data, start_step=start_step,
                              sampler=sampler)
    final_step = int(metrics.get("step", start_step))
    log(f"moe: done step={final_step} "
        f"loss={metrics.get('loss', -1):.4f}")
    loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
