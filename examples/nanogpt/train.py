"""nanoGPT-style elastic training example — the doc-of-record that the
whole stack composes outside pytest.

Capability parity: the reference's `examples/pytorch/nanogpt/train.py`
(trained via ElasticTrainer, :289) — TPU re-design on this framework's
stack: `dlrover-tpu-run --standalone` spawns a local master + agent; this
worker joins the process set, builds the model through `auto_accelerate`,
and trains with the elastic loop (checkpoint + sampler resume, step
reports to the master's SpeedMonitor).

Run single-host:
    python -m dlrover_tpu.run --standalone examples/nanogpt/train.py \
        --steps 200 --ckpt-dir /tmp/nanogpt-ckpt
Multi-node (per node):
    python -m dlrover_tpu.run --nnodes 2:4 --node-rank $RANK \
        --master-addr $DLROVER_TPU_MASTER_ADDR examples/nanogpt/train.py
On k8s, see manifests/samples/elasticjob_llama.yaml.

A SIGKILL mid-run (or a node loss) restarts the worker through the agent;
this script then resumes from the latest committed checkpoint with the
data position intact.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def parse_args(argv=None):
    parser = argparse.ArgumentParser("nanogpt-train")
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--global-batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=64)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--save-interval", type=int, default=20)
    parser.add_argument("--log-file", default="",
                        help="append step logs here (tests parse it)")
    return parser.parse_args(argv)


def synthetic_corpus(vocab_size: int, length: int = 2 ** 15) -> np.ndarray:
    """A deterministic token stream with local structure (random walk),
    standing in for the reference's shakespeare download."""
    rng = np.random.default_rng(1234)
    steps = rng.integers(-3, 4, length)
    return np.cumsum(steps).astype(np.int32) % vocab_size


def batches(corpus, sampler, global_batch, seq):
    """Yield (tokens, targets) global batches by sampler order."""
    starts_per_sample = len(corpus) - seq - 1
    batch = []
    for idx in sampler:
        start = idx % starts_per_sample
        batch.append(corpus[start:start + seq + 1])
        if len(batch) == global_batch:
            chunk = np.stack(batch)
            batch = []
            yield chunk[:, :-1], chunk[:, 1:]


def main(argv=None) -> int:
    args = parse_args(argv)

    from dlrover_tpu.agent.elastic_agent import init_distributed

    init_distributed()   # joins the round's process set; no-op single host

    import jax
    import optax

    from dlrover_tpu.models.gpt import GPT, GPTConfig
    from dlrover_tpu.models.llama import cross_entropy_loss
    from dlrover_tpu.trainer.elastic_loop import (
        ElasticTrainLoop,
        TrainLoopConfig,
    )
    from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

    cfg = GPTConfig.nano(
        attn_impl="flash" if jax.default_backend() == "tpu"
        else "reference")
    model = GPT(cfg)

    client = None
    if os.environ.get("DLROVER_TPU_MASTER_ADDR"):
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient.singleton()

    loop = ElasticTrainLoop(
        model,
        optax.adamw(args.lr, weight_decay=0.1),
        cross_entropy_loss,
        TrainLoopConfig(
            global_batch=args.global_batch,
            seq_len=args.seq,
            max_steps=args.steps,
            checkpoint_dir=args.ckpt_dir,
            save_interval_steps=args.save_interval,
            report_interval_steps=10,
        ),
        master_client=client,
    )
    loop.install_signal_handler()

    corpus = synthetic_corpus(cfg.vocab_size)
    sampler = ElasticDistributedSampler(
        dataset_size=10 ** 6, shuffle=True, seed=0)
    state, start_step = loop.restore_or_init(jax.random.PRNGKey(0),
                                             sampler)

    def log(message: str) -> None:
        print(message, flush=True)
        if args.log_file:
            with open(args.log_file, "a") as f:
                f.write(message + "\n")

    log(f"nanogpt: start_step={start_step} "
        f"dp={loop.dp} accum={loop.accum} backend={jax.default_backend()}")
    if args.steps <= start_step:
        log("nanogpt: nothing to do")
        loop.close()
        return 0

    data = batches(corpus, sampler, args.global_batch, args.seq)
    loop.config.max_steps = args.steps - start_step
    state, metrics = loop.run(state, data, start_step=start_step,
                              sampler=sampler)
    final_step = int(metrics.get("step", start_step))
    log(f"nanogpt: done step={final_step} loss={metrics.get('loss', -1):.4f}")
    loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
