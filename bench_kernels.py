"""Standalone kernel benchmark: flash-attention TF/s at bench shapes.

VERDICT r4 weak 6: the headline MFU wall is the attention kernel — the
MLP matmul runs at ~98% of peak, so the next MFU points live here. This
measures the Pallas kernel's effective TF/s (fwd and fwd+bwd) against
the XLA reference at the shapes the headline bench uses, so kernel
surgery has a number to move. Prints one JSON line per config.

FLOP accounting: causal attention does 2*s*s*d FLOPs per (batch, head)
for QK^T and the same for PV, halved by causality -> fwd
2*b*h*s*s*d. Backward recomputes fwd block products and adds dQ/dK/dV
products: ~2.5x fwd FLOPs (standard flash accounting).

Run on the chip: `python bench_kernels.py`. Off-TPU it falls back to a
tiny interpret-mode sanity shape (numbers meaningless there).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _force(out) -> float:
    """Force execution with a host transfer of one scalar. On the axon
    tunnel `block_until_ready` does not actually wait; pulling a scalar
    does, and device execution is in-order, so forcing the last step's
    output proves all prior steps finished."""
    leaf = jax.tree.leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def bench_one(fn, args, reps=20, timed_calls=3):
    """Time `fn` amortized over `reps` sequential calls INSIDE one jitted
    program (a scan whose carry perturbs q each iteration, so calls can't
    be CSE'd) — per-call dispatch through the axon tunnel costs ~3 ms,
    which swamps a ~1 ms kernel when timed call-by-call; inside the
    model's jitted step the kernel pays no such cost."""
    q0, *rest = args

    @jax.jit
    def many(q, *rest):
        def body(c, _):
            o = fn(c, *rest)
            lead = jax.tree.leaves(o)[0]
            return c + 1e-6 * lead.astype(c.dtype), None

        c, _ = jax.lax.scan(body, q, None, length=reps)
        return c

    out = many(q0, *rest)          # compile + warm
    _force(out)
    t0 = time.perf_counter()
    for _ in range(timed_calls):
        out = many(q0, *rest)
    _force(out)
    return (time.perf_counter() - t0) / (timed_calls * reps)


def main() -> None:
    from dlrover_tpu.agent.elastic_agent import apply_jax_platform_env

    apply_jax_platform_env()
    from dlrover_tpu.models.llama import reference_attention
    from dlrover_tpu.ops.flash_attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # headline bench shape (llama_wide_1b at micro 2, seq 2048) and
        # a 7B-shaped config
        configs = [
            ("bench_1b", 2, 16, 2048, 128),
            ("llama7b", 1, 32, 2048, 128),
            ("long_8k", 1, 16, 8192, 128),
        ]
        variants = [("flash", dict(block_q=1024, block_k=1024)),
                    ("flash_512", dict(block_q=512, block_k=512)),
                    ("xla_ref", None)]
    else:
        configs = [("tiny", 1, 2, 256, 64)]
        variants = [("flash", dict(block_q=128, block_k=128)),
                    ("xla_ref", None)]

    rng = np.random.default_rng(0)
    for name, b, h, s, d in configs:
        q, k, v = (jnp.asarray(rng.normal(size=(b, h, s, d)),
                               jnp.bfloat16) for _ in range(3))
        fwd_flops = 2 * 2 * b * h * s * s * d / 2   # causal half
        for vname, kwargs in variants:
            if kwargs is None:
                f = jax.jit(lambda q, k, v: reference_attention(
                    q, k, v, True))
            else:
                kw = dict(kwargs)
                f = jax.jit(lambda q, k, v, _kw=kw: flash_attention(
                    q, k, v, True, **_kw))
            try:
                dt_f = bench_one(f, (q, k, v))

                def loss(q, k, v, _f=f):
                    return jnp.sum(_f(q, k, v).astype(jnp.float32))

                g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                dt_b = bench_one(g, (q, k, v))
                print(json.dumps({
                    "config": name, "variant": vname,
                    "fwd_ms": round(dt_f * 1e3, 3),
                    "fwd_tflops": round(fwd_flops / dt_f / 1e12, 1),
                    "fwdbwd_ms": round(dt_b * 1e3, 3),
                    "fwdbwd_tflops": round(
                        3.5 * fwd_flops / dt_b / 1e12, 1),
                }))
            except Exception as e:
                print(json.dumps({"config": name, "variant": vname,
                                  "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
