"""Fleet-controller benchmark: goodput with the controller riding a
scripted preemptible-capacity market vs the same schedule uncontrolled.

The story being measured (ISSUE 18): the master's fleet controller
(brain/fleet_controller.py) closes the diagnosis→actuation loop — it
claims an offered preemptible slice when the predicted marginal goodput
beats the join+re-plan cost, books a market revocation through the
PR 5 drain path, and prices every move in the goodput ledger under the
``autoscale`` elasticity kind.

Both legs run the SAME wall-clock schedule against a real in-process
JobMaster (warm → capacity offer → grown window with one 3×-slow
straggler rank → revocation + clean drain → tail):

- ``controller_on``  — the controller claims the offer (hysteresis,
                       economics and guardrails all live), the granted
                       rank joins and reports, the revoke drains it;
- ``controller_off`` — the identical market events happen but nothing
                       claims, so the offered capacity never produces.

Prints ONE JSON line:
    {"metric": "autoscale_goodput_gain", "value": R, ...,
     "controller_on": {...}, "controller_off": {...}}

where ``value`` is productive rank-seconds (from the master's own
ledger) controller-on over controller-off; > 1.0 means riding the offer
paid for the claim. ``--smoke`` shrinks the schedule for CI.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _ensure_cpu_devices(n: int) -> None:
    """Before jax imports: virtual CPU devices (no-op on accelerators)."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and \
            "JAX_PLATFORMS" not in os.environ:
        os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}"
        ).strip()


def _wait_world(client, size: int, timeout_s: float = 10.0) -> dict:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        _, _, world = client.get_comm_world()
        if world and len(world) >= size:
            return world
        time.sleep(0.02)
    raise TimeoutError(f"world of {size} never formed")


def run_leg(controller_on: bool, warm: int, grown: int, tail: int,
            tick_s: float) -> dict:
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.config import Context
    from dlrover_tpu.master.job_master import JobMaster

    ctx = Context.singleton()
    saved = ctx.fleet_controller_enabled
    ctx.fleet_controller_enabled = controller_on
    master = JobMaster(port=0, min_nodes=1, max_nodes=2,
                       host="127.0.0.1")
    master.prepare()
    leg_started = time.time()
    # NOTE: no slice_id — slice-scoped rendezvous routes joins to the
    # per-slice cut path and a fleet round of [0, 1] would never cut;
    # the bench measures fleet growth, so the clients stay sliceless.
    c0 = MasterClient(master.addr, node_id=0, node_rank=0)
    c1 = None
    step = 0
    decisions: list = []
    straggler_scores: dict = {}
    world_peak = 1
    try:
        c0.join_rendezvous(local_world_size=1)
        _wait_world(c0, 1)

        def tick(clients):
            nonlocal step
            step += 1
            for client, slow in clients:
                client.report_global_step(
                    step, step_time_s=tick_s * (3.0 if slow else 1.0),
                    data_wait_fraction=0.05)
            time.sleep(tick_s)

        # phase 1: warm — the ledger accrues the measured goodput the
        # claim economics need (claiming blind is refused by design)
        for _ in range(warm):
            tick([(c0, False)])

        # phase 2: the market offers one preemptible slice
        if controller_on:
            provider = master.capacity_provider

            def grant(offer):
                nonlocal c1, world_peak
                c1 = MasterClient(master.addr, node_id=1, node_rank=1)
                try:
                    c1.join_rendezvous(local_world_size=1)
                    c0.join_rendezvous(local_world_size=1)
                    _wait_world(c0, 2)
                except Exception:
                    # a grant that never formed the world must not leave
                    # a half-joined rank reporting into the ledger
                    c1.close()
                    c1 = None
                    raise
                world_peak = 2
                return [1]

            provider.grant_fn = grant
            provider.offer(slices=1, ttl_s=600.0, step=step)
            # two rounds: hysteresis demands consecutive windows of the
            # same candidate before the claim actuates
            for _ in range(
                    ctx.autoscale_hysteresis_windows + 1):
                record = master.fleet_controller.evaluate_once()
                if record is not None:
                    decisions.append({"kind": record["kind"],
                                      "reason": record["reason"]})
                if c1 is not None:
                    break

        # phase 3: the grown window — the claimed rank produces, but as
        # a 3×-slow straggler (the dispatch-weighting evidence)
        for _ in range(grown):
            members = [(c0, False)]
            if c1 is not None:
                members.append((c1, True))
            tick(members)
        straggler_scores = {
            str(rank): round(score, 3)
            for rank, score in
            master.speed_monitor.relative_speeds().items()}

        # phase 4: the market takes the slice back; the revoke books
        # through the provider and the slice drains cleanly (PR 5 path)
        if controller_on and c1 is not None:
            master.capacity_provider.revoke(1, grace_s=2.0, step=step)
            c1.report_drain(deadline=time.time() + 2.0,
                            reason="capacity revoked", phase="notice")
            time.sleep(0.05)
            c1.report_drain(deadline=0, phase="complete")
            c1.close()
            c1 = None
            c0.join_rendezvous(local_world_size=1)
            _wait_world(c0, 1)

        # phase 5: tail — back to owned capacity only
        for _ in range(tail):
            tick([(c0, False)])

        snap = master.goodput_ledger.snapshot()
        productive = sum(float(inc.get("productive", 0.0))
                         for inc in snap.get("incarnations", []))
        window = master.goodput_ledger.window_summary(3600.0)
        status = (master.fleet_controller.status()
                  if master.fleet_controller is not None else {})
        elapsed = max(1e-9, time.time() - leg_started)
        return {
            "productive_rank_seconds": round(productive, 3),
            # productive rank-seconds per wall second of the leg — the
            # windowed goodput both legs are compared on (same wall
            # schedule, so the rate is the fair cross-leg measure; the
            # ledger's own goodput_fraction divides by PRESENT
            # rank-seconds and penalizes the on-leg for having ridden
            # a second, join-cost-paying slice at all)
            "goodput_rate": round(productive / elapsed, 4),
            "leg_elapsed_s": round(elapsed, 3),
            "goodput_fraction": round(
                float(window.get("goodput_fraction", -1.0)), 4),
            "world_peak": world_peak,
            "final_step": step,
            "decisions": decisions,
            "decision_history": [
                {"kind": d.get("kind"), "outcome": d.get("outcome"),
                 "reason": d.get("reason")}
                for d in status.get("decisions", [])],
            "incarnation_reasons": [
                inc.get("reason")
                for inc in snap.get("incarnations", [])],
            "straggler_scores": straggler_scores,
        }
    finally:
        if c1 is not None:
            c1.close()
        c0.close()
        master.stop(grace_s=0.1)
        ctx.fleet_controller_enabled = saved


def run_bench(smoke: bool) -> dict:
    warm, grown, tail, tick_s = ((6, 8, 3, 0.03) if smoke
                                 else (12, 24, 6, 0.05))
    on = run_leg(True, warm, grown, tail, tick_s)
    off = run_leg(False, warm, grown, tail, tick_s)
    base = off["productive_rank_seconds"]
    gain = (on["productive_rank_seconds"] / base) if base > 0 else 0.0
    return {
        "metric": "autoscale_goodput_gain",
        "value": round(gain, 3),
        "unit": ("productive rank-seconds, controller-on / "
                 "controller-off, same scripted offer/revoke/"
                 "straggler schedule"),
        "schedule": {"warm": warm, "grown": grown, "tail": tail,
                     "tick_s": tick_s, "smoke": smoke},
        "controller_on": on,
        "controller_off": off,
    }


def main() -> int:
    parser = argparse.ArgumentParser("bench_autoscale",
                                     description=__doc__)
    parser.add_argument("--smoke", action="store_true",
                        help="shrunk schedule for CI (same code paths)")
    ns = parser.parse_args()
    result = run_bench(ns.smoke)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    _ensure_cpu_devices(2)
    raise SystemExit(main())
