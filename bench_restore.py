"""Elastic-restore benchmark: SIGKILL -> first step after restore, in seconds.

The north-star metric (BASELINE.md): elastic-restore wall-clock < 30 s after
a single-host kill. This bench runs the REAL stack — a standalone JobMaster,
an ElasticAgent, and a training worker subprocess using ElasticTrainLoop with
flash (async Orbax) checkpointing — then SIGKILLs the worker mid-training and
clocks kill -> failure detection -> re-rendezvous -> respawn -> restore ->
first completed step.

Prints ONE JSON line:
    {"metric": "elastic_restore_seconds", "value": S, "unit": "...",
     "vs_baseline": 30.0 / S}

Run directly (`python bench_restore.py`) or via bench.py, which folds the
number into the headline metric. Worker mode (`--worker`) is internal.

Reference behavior being measured: the agent restart path
(dlrover/python/elastic_agent/torch/training.py:429-521) combined with the
checkpoint-restore the reference left as a TODO
(dlrover/trainer/torch/elastic/trainer.py:295-319).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

# Keep this module import-light: the orchestrator must NOT touch the
# accelerator (the worker subprocess owns it).

KILL_AFTER_STEP = 4        # ensure a committed checkpoint exists (interval 2)
SAVE_INTERVAL = 2
GLOBAL_BATCH = 8
SEQ_LEN = 128

# --at-scale: the REAL bench model (1.47B wide-MLP Llama, bf16 params,
# factored-rms state — bench.py's headline config) so the clocked restore
# moves a multi-GB checkpoint through Orbax + device_put + re-jit, the
# actual cost the <30 s north star is about (VERDICT r3 item 1).
SCALE_GLOBAL_BATCH = 2
SCALE_SEQ_LEN = 2048


def _emit(events_file: str, event: dict) -> None:
    event = dict(event, t=time.time())
    with open(events_file, "a") as f:
        f.write(json.dumps(event) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _read_events(events_file: str) -> list:
    try:
        with open(events_file) as f:
            return [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        return []


# ---------------------------------------------------------------------------
# Worker (runs under the ElasticAgent)
# ---------------------------------------------------------------------------


def worker_main(ckpt_dir: str, events_file: str, total_steps: int,
                at_scale: bool = False, solo_replica: bool = False) -> int:
    from dlrover_tpu.agent.elastic_agent import (
        apply_jax_platform_env,
        init_distributed,
    )

    rank = int(os.environ.get("DLROVER_TPU_NODE_RANK", "0"))
    _emit(events_file, {"event": "worker_start", "pid": os.getpid(),
                        "rank": rank})
    if solo_replica:
        # --nodes N on the CPU backend: each worker is an independent
        # full DP replica (per-rank checkpoint dir, no cross-process
        # collectives — jax has no multi-process CPU SPMD). The control
        # plane, donor protocol and restore-plan delivery are exactly
        # the replicated multi-host configuration the peer path serves.
        apply_jax_platform_env()
    else:
        init_distributed()   # applies JAX_PLATFORMS + joins the process set

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models.llama import (
        Llama,
        LlamaConfig,
        cross_entropy_loss,
    )
    from dlrover_tpu.trainer.elastic_loop import (
        ElasticTrainLoop,
        TrainLoopConfig,
    )

    from dlrover_tpu.agent.master_client import MasterClient

    try:
        # step reports feed the master's goodput ledger (the bench's
        # goodput_fraction comes from the same accounting production
        # uses); report every step — this is a bench, not a hot loop
        client = MasterClient.singleton()
    except Exception:   # noqa: BLE001 — reports are optional evidence
        client = None

    if at_scale:
        on_tpu = jax.default_backend() == "tpu"
        cfg = LlamaConfig.llama_wide_1b(
            max_seq_len=SCALE_SEQ_LEN,
            attn_impl="flash" if on_tpu else "reference",
            embed_impl="gather",
            norm_impl="fused" if on_tpu else "reference",
            dtype=jnp.bfloat16,
        )
        tx = optax.chain(optax.scale_by_factored_rms(),
                         optax.scale(-3e-4))
        global_batch, seq_len = SCALE_GLOBAL_BATCH, SCALE_SEQ_LEN
    else:
        cfg = LlamaConfig.tiny(attn_impl="reference",
                               norm_impl="reference")
        tx = optax.adamw(3e-4)
        global_batch, seq_len = GLOBAL_BATCH, SEQ_LEN
    loop = ElasticTrainLoop(
        Llama(cfg),
        tx,
        cross_entropy_loss,
        TrainLoopConfig(
            global_batch=global_batch,
            seq_len=seq_len,
            checkpoint_dir=ckpt_dir,
            save_interval_steps=SAVE_INTERVAL,
            report_interval_steps=1,
        ),
        master_client=client,
    )
    loop.install_signal_handler()
    state, start = loop.restore_or_init(jax.random.PRNGKey(0))
    restored_event = {"event": "restored", "step": start, "rank": rank,
                      "timings": loop.last_restore_timings,
                      "restore_source": loop.last_restore_source}
    if os.environ.get("BENCH_RESTORE_STATE_CRC") == "1" and start > 0:
        # bitwise-identity evidence for the acceptance test: a CRC over
        # every restored leaf (host copies — tiny models only; the
        # at-scale bench must not pay a 5 GB device_get for it)
        import zlib

        from dlrover_tpu.checkpoint.peer_restore import (
            host_copy,
            shard_items,
        )

        crc = 0
        for _, leaf in shard_items(state):
            arr = host_copy(leaf)
            if arr is not None:
                crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(),
                                 crc)
        restored_event["state_crc"] = crc & 0xFFFFFFFF
    _emit(events_file, restored_event)

    restored_start = start
    if start > 0:
        # instrument the FIRST post-restore step in detail: dispatch
        # (includes any inline re-jit the AOT path failed to avoid) vs
        # force (execution + any deferred transfer)
        rng0 = np.random.default_rng(start)
        tokens = rng0.integers(0, cfg.vocab_size,
                               (global_batch, seq_len), dtype=np.int32)
        t0 = time.perf_counter()
        tok, tgt = loop.trainer.shard_batch(tokens, tokens)
        t1 = time.perf_counter()
        state, metrics = loop.trainer.step(state, tok, tgt)
        t2 = time.perf_counter()
        float(metrics["loss"])
        t3 = time.perf_counter()
        start += 1
        _emit(events_file, {
            "event": "step", "step": start, "rank": rank,
            "restored_from": restored_start,
            "first_step_detail": {
                "shard_batch_s": round(t1 - t0, 2),
                "dispatch_s": round(t2 - t1, 2),
                "force_s": round(t3 - t2, 2),
                "aot_used": getattr(loop.trainer, "last_used_aot",
                                    None),
            }})

    rng = np.random.default_rng(start)
    step = start
    while step < total_steps:
        tokens = rng.integers(0, cfg.vocab_size, (global_batch, seq_len),
                              dtype=np.int32)
        targets = rng.integers(0, cfg.vocab_size, (global_batch, seq_len),
                               dtype=np.int32)
        state, _ = loop.run(state, [(tokens, targets)], start_step=step)
        step += 1
        _emit(events_file, {"event": "step", "step": step, "rank": rank,
                            "restored_from": restored_start})
        if loop._stop_requested.is_set():
            break
    loop.close()
    return 0


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


def run_bench(timeout_s: float = 480.0, at_scale: bool = False,
              nodes: int = 1) -> dict:
    """nodes > 1 clocks the TRUE replacement-host story: N agents form
    one world, rank 0's worker is SIGKILLed AND its host-side peer cache
    wiped (a replacement host starts cold), so its shards must arrive
    over the donor protocol from the survivors — `restore_source: peer`
    with remote donors. nodes == 1 keeps the cache (a worker crash on a
    surviving host), so the peer path serves from local host RAM —
    that is what turns the 105 s at-scale Orbax round-trip into
    seconds."""
    from dlrover_tpu.agent.elastic_agent import ElasticAgent, WorkerSpec
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.job_master import JobMaster

    workdir = tempfile.mkdtemp(prefix="bench-restore-")
    ckpt_dir = os.path.join(workdir, "ckpt")
    events_file = os.path.join(workdir, "events.jsonl")

    master = JobMaster(min_nodes=nodes, max_nodes=nodes,
                       host="127.0.0.1")
    master.prepare()
    multi = nodes > 1
    # multi-node: per-rank checkpoint namespaces (each rank is a full DP
    # replica saving its own copy; the kill wipes rank 0's peer cache so
    # its shards must come over the donor protocol). rank 0's dir is the
    # one the Orbax path would have used — the clocked comparison.
    ckpt0 = os.path.join(ckpt_dir, "rank0") if multi else ckpt_dir

    def _entrypoint(rank: int):
        ep = [
            sys.executable, os.path.abspath(__file__), "--worker",
            "--ckpt-dir",
            os.path.join(ckpt_dir, f"rank{rank}") if multi else ckpt_dir,
            "--events-file", events_file,
        ]
        if at_scale:
            ep.append("--at-scale")
        if multi:
            ep.append("--solo-replica")
        return ep

    worker_env = {"JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0"}
    if multi and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # one virtual device per replica: an inherited
        # xla_force_host_platform_device_count (the test harness exports
        # 8) would multiply into a dp size the toy batch cannot divide
        worker_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    if at_scale:
        # Both incarnations share an on-disk compile cache: a restarted
        # process on the same host legitimately reuses it, and without
        # it the clocked restore is mostly XLA re-compile, not restore.
        worker_env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
            workdir, "compile_cache")
        # int8 params-only checkpoints (checkpoint/quantized.py): the
        # 1.47B state is 5.5 GB of fp32 masters, and at-scale restore
        # time is dominated by moving those bytes (measured 262 s raw);
        # the codec cuts them ~3.9x with no measurable resume-loss
        # impact, validated on the real chip round 5 (per-leaf encode,
        # 1.34 GB vs 5.08 GB, Orbax read 21.7 s vs ~95 s — see
        # docs/benchmarks.md "Round-5 on-chip evidence"), so int8 is
        # now the default; BENCH_RESTORE_QUANT_BITS=0 reverts to the
        # exact-dtype baseline.
        # pinned unconditionally: the worker env overlays the ambient
        # environment, so the codec choice is governed ONLY by
        # BENCH_RESTORE_QUANT_BITS — an exported
        # DLROVER_TPU_CKPT_QUANT_BITS must not silently re-quantize a
        # run explicitly reverted to the exact-dtype baseline with =0
        worker_env["DLROVER_TPU_CKPT_QUANT_BITS"] = os.environ.get(
            "BENCH_RESTORE_QUANT_BITS", "8")
    clients, agents, threads = [], [], []
    for rank in range(nodes):
        client = MasterClient(master.addr, node_id=rank, node_rank=rank)
        spec = WorkerSpec(
            entrypoint=_entrypoint(rank),
            devices_per_node=1,
            max_restarts=3,
            monitor_interval_s=0.2,
            enable_monitors=False,
            env=worker_env,
        )
        agent = ElasticAgent(client, spec)
        clients.append(client)
        agents.append(agent)
        thread = threading.Thread(target=agent.run, daemon=True)
        thread.start()
        threads.append(thread)
        if nodes > 1:
            time.sleep(0.2)   # stagger so all land in one round
    agent = agents[0]          # the victim's agent

    deadline = time.time() + timeout_s

    def _wait_for(predicate, what: str):
        while time.time() < deadline:
            events = _read_events(events_file)
            hit = predicate(events)
            if hit is not None:
                return hit
            time.sleep(0.05)
        raise TimeoutError(f"timed out waiting for {what}")

    def _committed_step() -> int:
        try:
            steps = [int(name) for name in os.listdir(ckpt0)
                     if name.isdigit()]
            return max(steps) if steps else 0
        except OSError:
            return 0

    def _rank0(event: dict) -> bool:
        return int(event.get("rank", 0)) == 0

    try:
        # Phase 1: train past a committed checkpoint (the step event
        # alone is not enough — the save is async, and killing before
        # the commit would clock a from-scratch restart, not a restore).
        _wait_for(
            lambda evs: next(
                (e for e in evs
                 if e["event"] == "step" and _rank0(e)
                 and e["step"] >= KILL_AFTER_STEP
                 and _committed_step() >= 2),
                None),
            f"step {KILL_AFTER_STEP} + committed checkpoint",
        )
        victim_pid = agent._proc.pid
        os.kill(victim_pid, signal.SIGKILL)
        if nodes > 1:
            # replacement-host simulation: the staged host cache died
            # with the host, so rank 0's shards MUST come from the
            # surviving donors over the wire
            import shutil

            shutil.rmtree(agent.peer_cache_dir, ignore_errors=True)
        t_kill = time.time()

        # Phase 2: agent detects the death, re-rendezvouses, respawns; the
        # new worker restores and completes its first step.
        first = _wait_for(
            lambda evs: next(
                (e for e in evs
                 if e["event"] == "step" and _rank0(e)
                 and e.get("restored_from", 0) > 0
                 and e["t"] > t_kill),
                None),
            "first step after restore",
        )
        events = _read_events(events_file)
        restored = next(
            e for e in events
            if e["event"] == "restored" and _rank0(e)
            and e["t"] > t_kill)
        elapsed = first["t"] - t_kill
        ckpt_bytes = 0
        # in multi mode rank 0 may have restored a step only the donor
        # committed (the survivor trained past the victim's last save):
        # size the restored step from whichever replica holds it
        candidates = ([ckpt0] + [os.path.join(ckpt_dir, f"rank{r}")
                                 for r in range(1, nodes)]
                      if multi else [ckpt_dir])
        for base in candidates:
            step_dir = os.path.join(base, str(restored["step"]))
            if os.path.isdir(step_dir):
                for root, _, files in os.walk(step_dir):
                    ckpt_bytes += sum(
                        os.path.getsize(os.path.join(root, f))
                        for f in files)
                break
        # per-phase breakdown of the kill -> first-step window: detect/
        # respawn (kill -> worker_start), jax + loop build (worker_start
        # -> restore phases, from the worker's own timings), first step
        breakdown = dict(restored.get("timings") or {})
        respawn = next(
            (e for e in events
             if e["event"] == "worker_start" and _rank0(e)
             and e["t"] > t_kill), None)
        # the top-level phases that partition kill -> first step
        # exclusively (the restore_* sub-phases nest inside
        # orbax_read_s, and peer_bytes/bandwidth are not durations).
        # peer_plan_s + peer_transfer_s are the peer path's read; on the
        # mixed path orbax_read_s additionally covers the shard-wise
        # storage fallback — the phases stay disjoint either way.
        exclusive = ("detect_respawn_s", "loop_build_s",
                     "abstract_state_s", "peer_plan_s",
                     "peer_transfer_s", "orbax_read_s",
                     "device_ready_s", "post_sync_s",
                     "compile_wait_after_read_s", "first_step_s")
        if respawn is not None:
            breakdown["detect_respawn_s"] = round(
                respawn["t"] - t_kill, 2)
            measured = sum(
                v for k, v in breakdown.items()
                if k in ("abstract_state_s", "peer_plan_s",
                         "peer_transfer_s", "orbax_read_s",
                         "device_ready_s", "post_sync_s",
                         "compile_wait_after_read_s"))
            breakdown["loop_build_s"] = round(
                restored["t"] - respawn["t"] - measured, 2)
        breakdown["first_step_s"] = round(first["t"] - restored["t"], 2)
        breakdown.update(first.get("first_step_detail") or {})
        phase_sum = sum(breakdown.get(k, 0.0) for k in exclusive)
        # the accounting's own acceptance: exclusive phases must explain
        # the headline number (within rounding + event-write jitter)
        result = {
            "elastic_restore_seconds": round(elapsed, 2),
            "restored_step": restored["step"],
            "first_step_after_restore": first["step"],
            "checkpoint_gb": round(ckpt_bytes / (1 << 30), 2),
            # where the replacement's state came from: "peer" (surviving
            # hosts' staged memory), "mixed" (peer + shard-wise Orbax),
            # "orbax" (full storage round-trip)
            "restore_source": restored.get("restore_source", "orbax"),
            "nodes": nodes,
            "breakdown": breakdown,
            "phase_sum_s": round(phase_sum, 2),
            "phase_coverage": round(phase_sum / elapsed, 3)
            if elapsed > 0 else 0.0,
        }
        if "state_crc" in restored:
            result["state_crc"] = restored["state_crc"]
        result["workdir"] = workdir
        result["ckpt_dir"] = ckpt0
        # the master's goodput ledger saw the whole episode through the
        # worker's step reports + telemetry spans: its productive
        # fraction + bucket split ride into the bench JSON so BENCH_r06+
        # tracks them beside the headline seconds
        snap = master.goodput_ledger.snapshot()
        result["goodput_fraction"] = snap.get("goodput_fraction", 0.0)
        result["goodput_buckets"] = {
            k: v for k, v in snap.get("buckets", {}).items() if v > 0.0}
        return result
    finally:
        for a in agents:
            a.shutdown()
        for c in clients:
            c.close()
        master.stop()


def main() -> int:
    parser = argparse.ArgumentParser("bench_restore")
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--events-file", default="")
    parser.add_argument("--total-steps", type=int, default=10**6)
    parser.add_argument("--timeout", type=float, default=480.0)
    parser.add_argument("--at-scale", action="store_true",
                        help="bench-headline 1.47B model: clock a "
                             "multi-GB restore (VERDICT r3 item 1)")
    parser.add_argument("--nodes", type=int, default=1,
                        help="agents in the world; > 1 wipes the "
                             "victim's host cache so its shards arrive "
                             "over the donor protocol (replacement-host "
                             "simulation)")
    parser.add_argument("--solo-replica", action="store_true",
                        help="worker mode: independent full DP replica "
                             "(no jax.distributed; per-rank checkpoint)")
    args = parser.parse_args()
    if args.worker:
        return worker_main(args.ckpt_dir, args.events_file,
                           args.total_steps, at_scale=args.at_scale,
                           solo_replica=args.solo_replica)
    result = run_bench(timeout_s=args.timeout, at_scale=args.at_scale,
                       nodes=args.nodes)
    seconds = result["elastic_restore_seconds"]
    metric = ("elastic_restore_seconds_at_scale" if args.at_scale
              else "elastic_restore_seconds")
    print(json.dumps({
        "metric": metric,
        "value": seconds,
        "unit": ("s (SIGKILL -> detect -> re-rendezvous -> respawn -> "
                 f"restore step {result['restored_step']} "
                 f"[{result['checkpoint_gb']} GB] -> first step; 1 host)"),
        "vs_baseline": round(30.0 / max(seconds, 1e-9), 2),
        "restore_source": result.get("restore_source", "orbax"),
        "breakdown": result.get("breakdown", {}),
        "checkpoint_gb": result["checkpoint_gb"],
        "phase_sum_s": result.get("phase_sum_s", 0.0),
        "phase_coverage": result.get("phase_coverage", 0.0),
        "goodput_fraction": result.get("goodput_fraction", 0.0),
        "goodput_buckets": result.get("goodput_buckets", {}),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
