// ElasticJob reconciler core.
//
// Capability parity: the Go operator's controller logic
// (dlrover/go/operator/pkg/controllers/elasticjob_controller.go:85
// Reconcile; master pod lifecycle master/master.go:53-162;
// HandleFaultPods master/master.go:165; ScalePlan relay
// scaleplan_controller.go). The reference implements this in Go against
// controller-runtime; here the decision core is a dependency-free C++
// library with a C ABI — the Python operator shell feeds it observed
// state and actuates the actions it returns, so the control decisions
// stay native and unit-testable.

#include <cstdint>
#include <cstring>

extern "C" {

// --- state vocabulary (keep in sync with dlrover_tpu/operator/native.py) ---
enum PodPhase : int32_t {
  POD_ABSENT = 0,
  POD_PENDING = 1,
  POD_RUNNING = 2,
  POD_SUCCEEDED = 3,
  POD_FAILED = 4,
};

enum JobPhase : int32_t {
  JOB_CREATED = 0,
  JOB_PENDING = 1,
  JOB_RUNNING = 2,
  JOB_SUCCEEDED = 3,
  JOB_FAILED = 4,
  JOB_SCALING = 5,
};

enum ActionKind : int32_t {
  ACT_NONE = 0,
  ACT_CREATE_MASTER = 1,     // create the job-master pod + service
  ACT_RELAUNCH_MASTER = 2,   // master pod died and budget remains
  ACT_SET_PHASE = 3,         // arg = JobPhase
  ACT_RELAY_SCALE_PLAN = 4,  // forward manual ScalePlan to the master
  ACT_FAIL_JOB = 5,          // arg = reason code
};

struct JobObserved {
  int32_t job_phase;           // current recorded phase
  int32_t master_phase;        // PodPhase of the master pod
  int32_t master_restarts;     // times the master has been relaunched
  int32_t max_master_restarts;
  int32_t suspended;           // job paused by the user
  int32_t pending_scale_plan;  // a ScalePlan CR awaits relay
  int32_t workers_total;
  int32_t workers_running;
  int32_t workers_succeeded;
  int32_t workers_failed_unrecoverable;
};

struct Action {
  int32_t kind;
  int32_t arg;
};

// Compute the next actions for one reconcile pass. Returns the number of
// actions written (<= max_actions). Mirrors ElasticJobReconciler.Reconcile:
// the operator only manages the MASTER; workers belong to the master.
int32_t reconcile_elastic_job(const JobObserved* job, Action* out,
                              int32_t max_actions) {
  int32_t n = 0;
  auto emit = [&](int32_t kind, int32_t arg) {
    if (n < max_actions) {
      out[n].kind = kind;
      out[n].arg = arg;
      ++n;
    }
  };

  if (job->suspended) {
    return n;  // suspended jobs reconcile to nothing
  }
  // Terminal phases are sticky.
  if (job->job_phase == JOB_SUCCEEDED || job->job_phase == JOB_FAILED) {
    return n;
  }

  switch (job->master_phase) {
    case POD_ABSENT:
      emit(ACT_CREATE_MASTER, 0);
      if (job->job_phase != JOB_PENDING) emit(ACT_SET_PHASE, JOB_PENDING);
      break;
    case POD_PENDING:
      if (job->job_phase != JOB_PENDING) emit(ACT_SET_PHASE, JOB_PENDING);
      break;
    case POD_RUNNING:
      if (job->job_phase != JOB_RUNNING) emit(ACT_SET_PHASE, JOB_RUNNING);
      if (job->pending_scale_plan) emit(ACT_RELAY_SCALE_PLAN, 0);
      break;
    case POD_SUCCEEDED:
      // master exits 0 when the job finished (all workers done)
      emit(ACT_SET_PHASE, JOB_SUCCEEDED);
      break;
    case POD_FAILED:
      // HandleFaultPods: relaunch the master within budget, else fail
      if (job->master_restarts < job->max_master_restarts) {
        emit(ACT_RELAUNCH_MASTER, job->master_restarts + 1);
      } else {
        emit(ACT_FAIL_JOB, 1);
        emit(ACT_SET_PHASE, JOB_FAILED);
      }
      break;
  }

  // Worker-status roll-up (job phase sync from replica statuses): the
  // master normally reports completion itself; this is the safety net
  // when every worker reached a terminal state but the master is gone.
  if (job->master_phase == POD_ABSENT && job->workers_total > 0) {
    if (job->workers_succeeded == job->workers_total) {
      emit(ACT_SET_PHASE, JOB_SUCCEEDED);
    } else if (job->workers_failed_unrecoverable == job->workers_total) {
      emit(ACT_FAIL_JOB, 2);
      emit(ACT_SET_PHASE, JOB_FAILED);
    }
  }
  return n;
}

// Version tag so the Python shell can verify ABI compatibility.
int32_t reconciler_abi_version() { return 1; }

}  // extern "C"
