// Shared-memory ring buffer for host-side data pipelines.
//
// Capability parity: atorch's ShmDataContext (atorch/data/shm_context.py:139)
// — the shared-memory IPC ring that moves preprocessed batches from CPU
// "coworker" processes into the training process without pickling through
// sockets. The reference implements it in Python over
// multiprocessing.shared_memory; here the hot path (variable-size record
// ring with blocking push/pop) is C++ with C linkage for ctypes.
//
// Layout: [Header | data bytes...]; records are [u32 len | payload]
// wrapped at the end with a SKIP sentinel. Single-producer/single-consumer
// per ring (the Python layer shards multiple workers over multiple rings,
// like the reference's per-worker shm blocks); head/tail are C11 atomics so
// push/pop need no locks.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x444c5452;  // "DLTR"
constexpr uint32_t kSkip = 0xffffffff;   // wrap sentinel

struct Header {
  uint32_t magic;
  uint32_t capacity;                 // data bytes
  std::atomic<uint64_t> head;        // write offset (mod capacity)
  std::atomic<uint64_t> tail;        // read offset (mod capacity)
  std::atomic<uint32_t> closed;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_size;
  int fd;
  bool owner;
  char name[256];
};

inline uint64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

inline void sleep_us(long us) {
  timespec ts{0, us * 1000};
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring named `name` with `capacity`
// data bytes. Returns an opaque handle or null.
void* shm_ring_open(const char* name, uint32_t capacity, int owner) {
  int flags = owner ? (O_CREAT | O_RDWR | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0 && owner && errno == EEXIST) {
    shm_unlink(name);  // stale ring from a dead process
    fd = shm_open(name, flags, 0600);
  }
  if (fd < 0) return nullptr;
  size_t map_size = sizeof(Header) + capacity;
  if (owner && ftruncate(fd, (off_t)map_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!owner) {
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_size = (size_t)st.st_size;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    if (owner) shm_unlink(name);
    return nullptr;
  }
  Ring* ring = new Ring();
  ring->hdr = (Header*)mem;
  ring->data = (uint8_t*)mem + sizeof(Header);
  ring->map_size = map_size;
  ring->fd = fd;
  ring->owner = owner != 0;
  snprintf(ring->name, sizeof(ring->name), "%s", name);
  if (owner) {
    ring->hdr->magic = kMagic;
    ring->hdr->capacity = capacity;
    ring->hdr->head.store(0);
    ring->hdr->tail.store(0);
    ring->hdr->closed.store(0);
  } else if (ring->hdr->magic != kMagic) {
    munmap(mem, map_size);
    close(fd);
    delete ring;
    return nullptr;
  }
  return ring;
}

uint32_t shm_ring_capacity(void* handle) {
  return ((Ring*)handle)->hdr->capacity;
}

// Push one record. Blocks up to timeout_ms for space. Returns 0 ok,
// -1 timeout, -2 closed, -3 record too large.
int shm_ring_push(void* handle, const uint8_t* buf, uint32_t len,
                  int64_t timeout_ms) {
  Ring* r = (Ring*)handle;
  Header* h = r->hdr;
  const uint32_t cap = h->capacity;
  const uint32_t need = len + 4;
  if (need + 4 > cap) return -3;  // must leave room for a wrap sentinel
  const uint64_t deadline = now_ms() + (timeout_ms < 0 ? 0 : timeout_ms);
  for (;;) {
    if (h->closed.load(std::memory_order_acquire)) return -2;
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t used = head - tail;
    uint32_t pos = (uint32_t)(head % cap);
    uint32_t to_end = cap - pos;
    // a record never wraps: if it doesn't fit before the end, write a
    // SKIP sentinel and start at 0 (consumer mirrors this)
    uint32_t effective = (to_end >= need) ? need : to_end + need;
    if (cap - used >= effective) {
      if (to_end < need) {
        if (to_end >= 4) memcpy(r->data + pos, &kSkip, 4);
        head += to_end;
        pos = 0;
      }
      memcpy(r->data + pos, &len, 4);
      memcpy(r->data + pos + 4, buf, len);
      h->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (timeout_ms >= 0 && now_ms() >= deadline) return -1;
    sleep_us(100);
  }
}

// Peek next record length without consuming. Returns length, 0 if empty,
// -2 if closed and drained.
int64_t shm_ring_next_len(void* handle) {
  Ring* r = (Ring*)handle;
  Header* h = r->hdr;
  const uint32_t cap = h->capacity;
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) {
      return h->closed.load(std::memory_order_acquire) ? -2 : 0;
    }
    uint32_t pos = (uint32_t)(tail % cap);
    uint32_t to_end = cap - pos;
    uint32_t len;
    if (to_end < 4) {  // implicit skip (sentinel didn't fit either)
      h->tail.store(tail + to_end, std::memory_order_release);
      continue;
    }
    memcpy(&len, r->data + pos, 4);
    if (len == kSkip) {
      h->tail.store(tail + to_end, std::memory_order_release);
      continue;
    }
    return (int64_t)len;
  }
}

// Pop one record into buf (buf_len must be >= record length). Blocks up to
// timeout_ms. Returns record length, -1 timeout, -2 closed+drained,
// -3 buffer too small.
int64_t shm_ring_pop(void* handle, uint8_t* buf, uint32_t buf_len,
                     int64_t timeout_ms) {
  Ring* r = (Ring*)handle;
  Header* h = r->hdr;
  const uint32_t cap = h->capacity;
  const uint64_t deadline = now_ms() + (timeout_ms < 0 ? 0 : timeout_ms);
  for (;;) {
    int64_t len = shm_ring_next_len(handle);
    if (len > 0) {
      if ((uint32_t)len > buf_len) return -3;
      uint64_t tail = h->tail.load(std::memory_order_relaxed);
      uint32_t pos = (uint32_t)(tail % cap);
      memcpy(buf, r->data + pos + 4, (size_t)len);
      h->tail.store(tail + (uint32_t)len + 4, std::memory_order_release);
      return len;
    }
    if (len == -2) return -2;
    if (timeout_ms >= 0 && now_ms() >= deadline) return -1;
    sleep_us(100);
  }
}

void shm_ring_mark_closed(void* handle) {
  ((Ring*)handle)->hdr->closed.store(1, std::memory_order_release);
}

// Unmap; the owner also unlinks the shm object.
void shm_ring_close(void* handle) {
  Ring* r = (Ring*)handle;
  bool owner = r->owner;
  char name[256];
  snprintf(name, sizeof(name), "%s", r->name);
  munmap((void*)r->hdr, r->map_size);
  close(r->fd);
  delete r;
  if (owner) shm_unlink(name);
}

}  // extern "C"
