// Custom host-op extension point (tfplus-equivalent).
//
// Capability parity: tfplus's demo custom op (tfplus/tfplus/cc/demo.{h,cc}
// — the reference's skeleton showing where users bolt native C++ ops onto
// the framework). TPU re-design: device-side custom ops are Pallas kernels
// (ops/flash_attention.py, ops/quantization.py); HOST-side native ops are
// plain C-linkage functions in this library, surfaced to Python via ctypes
// (dlrover_tpu/ops/host_ops.py) and into jit programs via
// jax.pure_callback. The two ops here are real, not placeholders: a
// zlib-compatible CRC32 for batch-integrity checks on the data plane, and
// a token histogram for input-skew diagnostics.

#include <cstdint>
#include <cstring>

namespace {

// zlib CRC-32 (reflected, poly 0xEDB88320), table generated on first use.
const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  return table;
}

}  // namespace

extern "C" {

// Matches zlib.crc32(data, seed): callers chain batches by feeding the
// previous result back as seed.
uint32_t dlrover_tpu_crc32(const uint8_t* data, uint64_t n, uint32_t seed) {
  const uint32_t* table = crc_table();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (uint64_t i = 0; i < n; ++i)
    c = table[(c ^ data[i]) & 0xFFu] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Counts token ids into out[vocab] (uint64, caller-zeroed or not —
// counts are ADDED so shards can accumulate). Ids outside [0, vocab)
// are counted into out[vocab] when out has vocab+1 slots per the
// `clamp_oov` flag; with clamp_oov=0 they are skipped. Returns the
// number of out-of-vocab tokens seen.
uint64_t dlrover_tpu_token_histogram(const int32_t* tokens, uint64_t n,
                                     uint64_t* out, uint32_t vocab,
                                     int clamp_oov) {
  uint64_t oov = 0;
  for (uint64_t i = 0; i < n; ++i) {
    int32_t t = tokens[i];
    if (t >= 0 && static_cast<uint32_t>(t) < vocab) {
      ++out[t];
    } else {
      ++oov;
      if (clamp_oov) ++out[vocab];
    }
  }
  return oov;
}

}  // extern "C"
