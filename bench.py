"""Headline benchmark: Llama train-step throughput + MFU on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the driver target of 40% MFU for Llama-class training
(BASELINE.md; reference HFU claim 49.6% on GPU,
docs/blogs/stabilize_llm_training_cn.md:352-353).

On TPU this benches a Llama at seq 2048 in bf16 with the Pallas
flash-attention kernel (1024x1024 blocks, bf16 MXU inputs + fp32
accumulation) and the fused Pallas RMSNorm; the model size is picked to
fit the chip's HBM with adafactor's factored optimizer state (the lean
state is what lets a 16 GB chip train a hidden-2048 model, which is worth
+0.13 MFU over the adamw-sized alternative). Off-TPU (dev machines) it
falls back to a tiny config so the script stays runnable anywhere.

MFU accounting is conservative: flops/token = 6·params + 6·L·h·s (the
causal-discounted attention term — half the PaLM-style 12·L·h·s — matching
what the kernel actually computes, since blocks above the diagonal are
skipped). Embedding lookup FLOPs are excluded, so the single-chip bench
uses the cheaper gather lookup rather than crediting itself the one-hot
matmul.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models.llama import Llama, LlamaConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshSpec, create_mesh
from dlrover_tpu.trainer.train_step import build_trainer

# bf16 peak FLOP/s per chip by device kind: single-sourced in
# obs/mfu.py (the framework's MFU gauges and this bench must agree)
from dlrover_tpu.obs import mfu as mfu_math  # noqa: E402


def peak_flops(device) -> float:
    return mfu_math.peak_flops_per_chip(
        getattr(device, "device_kind", ""),
        backend=jax.default_backend())


def probe_tpu(timeout_s: float = 120.0) -> bool:
    """Check the accelerator is reachable from a SUBPROCESS with a hard
    timeout: a wedged TPU tunnel hangs backend init forever, and the
    driver's bench must degrade to CPU rather than stall."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return "ok" in proc.stdout
    except Exception:
        return False


def _run_json_subprocess(cmd, timeout_s: float, env=None) -> dict:
    """Run cmd in its own process group, parse the last JSON line of
    stdout. On timeout the whole group is SIGKILLed (a worker grandchild
    may hold the single-client accelerator tunnel). Returns {"error":
    ...} on any failure."""
    import signal
    import subprocess

    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True, env=env,
    )
    try:
        stdout, _ = proc.communicate(timeout=timeout_s)
        for line in reversed(stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        return {"error": f"timed out after {timeout_s}s"}
    except Exception as e:
        return {"error": str(e)[:200]}
    return {"error": "no result line"}


def run_restore_bench(timeout_s: float = 480.0,
                      at_scale: bool = False) -> dict:
    """Run bench_restore.py in a subprocess tree. The toy mode is
    CPU-staged (JAX_PLATFORMS=cpu for the whole tree): it measures the
    REAL elastic stack — kill detection, re-rendezvous, respawn, orbax
    restore — without competing for the single-client TPU tunnel. The
    --at-scale mode runs the 1.47B bench model ON the chip (multi-GB
    restore + re-jit, VERDICT r3 item 1); it must run while no other
    process holds the TPU. Returns the bench's JSON record ("value" =
    seconds, plus the per-phase breakdown and goodput summary); an
    {"error": ...}-shaped dict on failure."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_restore.py")
    env = dict(os.environ)
    cmd = [sys.executable, script, "--timeout", str(timeout_s)]
    if at_scale:
        cmd.append("--at-scale")
    else:
        env["JAX_PLATFORMS"] = "cpu"
    return _run_json_subprocess(cmd, timeout_s + 60, env=env)


def _restore_seconds(restore_result: dict) -> float:
    try:
        return float(restore_result["value"])
    except (KeyError, TypeError, ValueError):
        return -1.0


def _fold_restore_fields(result: dict, restore_result: dict) -> None:
    """Fold the restore bench's per-phase breakdown + goodput summary
    into the scoreboard record (BENCH_r06+ tracks these beside the
    headline seconds): where each restore second went, and how much of
    the episode's rank-time was productive."""
    breakdown = restore_result.get("breakdown") or {}
    for source, target in (
            ("peer_plan_s", "restore_peer_plan_s"),
            ("peer_transfer_s", "restore_peer_transfer_s"),
            ("peer_bandwidth_mbps", "restore_peer_bandwidth_mbps"),
            ("orbax_read_s", "restore_orbax_read_s"),
            ("restore_metadata_read_s", "restore_metadata_read_s"),
            ("restore_tensor_read_s", "restore_tensor_read_s"),
            ("restore_decode_s", "restore_decode_s"),
            ("device_ready_s", "restore_device_put_s"),
            ("post_sync_s", "restore_post_sync_s"),
            ("detect_respawn_s", "restore_detect_respawn_s"),
            ("compile_wait_after_read_s",
             "restore_compile_wait_s"),
            ("first_step_s", "restore_first_step_s"),
            ("restore_read_bandwidth_mbps",
             "restore_read_bandwidth_mbps"),
    ):
        if source in breakdown:
            result[target] = breakdown[source]
    for key in ("phase_sum_s", "phase_coverage", "goodput_fraction",
                "goodput_buckets", "restore_source"):
        if key in restore_result:
            result[key] = restore_result[key]


def _timed_loop(step_fn, state, tok, tgt, warmup=2, steps=5,
                per_step=None):
    """Shared warmup + timed-window protocol. The float() host fetches
    force the full chain to execute — necessary under remote-execution
    backends (block_until_ready does not wait on the axon tunnel).
    ``per_step`` (optional list) collects each timed step's dispatch
    wall time for the critical-path fold — stamps only, no extra host
    syncs, so the headline window is unchanged.
    Returns (state, seconds, warmup_loss, final_loss)."""
    for _ in range(max(warmup, 1)):   # >=1: warmup_loss needs a metrics
        state, metrics = step_fn(state, tok, tgt)
    warmup_loss = float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        t_step = time.perf_counter()
        state, metrics = step_fn(state, tok, tgt)
        if per_step is not None:
            per_step.append(time.perf_counter() - t_step)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    return state, dt, warmup_loss, final_loss


def _critical_path_summary(step_times) -> dict:
    """The timed window folded through the fleet's steptrace solver
    (master/steptrace.py pure helpers) — the SAME attribution shape the
    master reports, so the bench JSON and the live dashboard speak one
    vocabulary. One lane here: a single-process bench has no cross-slice
    barrier, and the fold says so (wait fraction 0) instead of omitting
    the field."""
    from dlrover_tpu.master.steptrace import (
        solve_group,
        summarize_solved,
    )

    solved, t0 = [], 0.0
    for step, dt in enumerate(step_times):
        rec = {"step": step, "gen": 0, "slice": 0, "rank": 0,
               "t0": t0, "off": 0.0, "err": 0.0,
               "phases": [["compute", 0.0, float(dt)]], "peers": {}}
        solved.append(solve_group(0, step, {0: rec}))
        t0 += float(dt)
    summary = summarize_solved(solved)
    return {
        "traced_steps": summary["steps"],
        "dominant_gating_phase": summary["dominant_gating_phase"],
        "cross_slice_wait_fraction": summary[
            "cross_slice_wait_fraction"],
    }


def _model_flops_per_token(cfg, seq: int) -> float:
    """obs/mfu.py's conservative accounting: 6·params fwd+bwd matmul
    credit (a gather-lookup embedding table with untied output head
    does no matmul, so those params are not credited) plus the
    causal-discounted attention term — matching what the kernel
    actually computes."""
    uncounted = 0.0
    if cfg.embed_impl == "gather" and not cfg.tie_embeddings:
        uncounted = cfg.vocab_size * cfg.hidden_size
    return mfu_math.flops_per_token(
        cfg.param_count(), num_layers=cfg.num_layers,
        hidden_size=cfg.hidden_size, seq_len=seq,
        uncounted_embed_params=uncounted)


def _oom_report(e: Exception, **extra) -> int:
    """OOM and friends: the reason IS the result, not a failure."""
    reason = str(e)
    key = reason.find("memory space")
    if key >= 0:
        reason = reason[max(0, key - 160):key + 160]
    out = {"error": reason[:400]}
    out.update(extra)
    print(json.dumps(out))
    return 0


def _seven_b_streaming() -> int:
    """Llama-7B on a <20 GB chip via the streaming per-layer trainer
    (trainer/streaming.py): backward is a reverse per-layer loop that
    applies the factored-rms update in place, so only ONE layer's
    gradients are ever live — peak ≈ params + one layer's grads
    ≈ 14 GB, under the 15.75 GB that the dense step's full gradient
    tree (27 GB) overruns (VERDICT r4 item 3 / docs/benchmarks.md).
    AOT-compiles first and reports the XLA memory analysis either way,
    so an OOM comes with the measured budget, not a guess. micro 2
    measures ~6.5% faster than micro 1 (0.586 vs 0.550 MFU on v5e) at
    the same 15.48 GB analyzed peak; micro 1 stays as the fallback so a
    tighter-HBM chip still produces a number instead of an OOM note —
    with the micro-2 failure reason carried in the reported JSON
    (``fallback_note``), not lost on a discarded stderr."""
    try:
        print(json.dumps(_seven_b_streaming_run(2, 2048)))
        return 0
    except Exception as e:
        note = f"micro=2 failed ({str(e)[:300]}); fell back to micro=1"
    try:
        rec = _seven_b_streaming_run(1, 2048)
        rec["fallback_note"] = note
        print(json.dumps(rec))
        return 0
    except Exception as e:
        return _oom_report(e, mode="streaming",
                           memory=getattr(e, "bench_memory", {}),
                           fallback_note=note)


def _seven_b_streaming_run(micro: int, seq: int) -> dict:
    """One streaming-7B attempt. Returns the result record; raises on
    failure with the partial XLA memory analysis attached as
    ``e.bench_memory`` so the caller's report keeps the evidence."""
    from dlrover_tpu.trainer.streaming import build_streaming_trainer

    # untied embeddings — real Llama-7B has a separate lm_head; tying
    # would shave vocab·hidden params (~2%) and overstate the number
    cfg = LlamaConfig.llama_7b(
        max_seq_len=seq, attn_impl="flash", embed_impl="gather",
        norm_impl="fused", dtype=jnp.bfloat16,
        param_dtype=jnp.bfloat16)
    tx = optax.chain(optax.scale_by_factored_rms(),
                     optax.scale(-3e-4))
    mem: dict = {}
    try:
        trainer = build_streaming_trainer(cfg, tx, micro, seq)
        abstract = trainer.abstract_state(jax.random.PRNGKey(0))
        tok_abs = jax.ShapeDtypeStruct((micro, seq), jnp.int32)
        compiled = trainer.step_fn.lower(
            abstract, tok_abs, tok_abs).compile()
        stats = compiled.memory_analysis()
        if stats is not None:
            mem = {
                "args_gb": round(stats.argument_size_in_bytes / 2**30, 2),
                "temp_gb": round(stats.temp_size_in_bytes / 2**30, 2),
                "out_gb": round(stats.output_size_in_bytes / 2**30, 2),
                "alias_gb": round(stats.alias_size_in_bytes / 2**30, 2),
            }
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(
            0, cfg.vocab_size, (micro, seq), dtype=np.int32))
        # reuse the AOT executable: trainer.step would re-trace and pay
        # the (on-chip, minutes-long) compile a second time
        trainer.step_fn = lambda s, t, tg: compiled(s, t, tg)
        steps = 5
        _, dt, _, _ = _timed_loop(trainer.step, state, tokens, tokens,
                                  warmup=2, steps=steps)
        tokens_per_sec = micro * seq * steps / dt
        mfu = (tokens_per_sec * _model_flops_per_token(cfg, seq)
               / peak_flops(jax.devices()[0]))
        return {"tokens_per_sec": round(tokens_per_sec, 1),
                "mfu": round(mfu, 4), "mode": "streaming",
                "micro_batch": micro, "memory": mem}
    except Exception as e:
        e.bench_memory = mem
        raise


def seven_b_main() -> int:
    """--llama7b subprocess: an honest Llama-7B tokens/sec/chip attempt
    (VERDICT r3 item 2). On <20 GB chips the streaming per-layer
    trainer caps peak memory at params + one layer's grads (see
    _seven_b_streaming); on bigger chips the dense step measures
    directly. On OOM the XLA text is REPORTED as the measured reason
    rather than faked around. Prints one JSON line either way."""
    from dlrover_tpu.agent.elastic_agent import apply_jax_platform_env

    apply_jax_platform_env()
    try:
        if jax.default_backend() == "tpu":
            hbm = (jax.devices()[0].memory_stats() or {}).get(
                "bytes_limit", 16 << 30)
            if hbm < 20 << 30:
                return _seven_b_streaming()
        cfg = LlamaConfig.llama_7b(
            max_seq_len=2048, attn_impl="flash", remat=True,
            embed_impl="gather", norm_impl="fused", dtype=jnp.bfloat16,
            # pure-bf16 params: fp32 masters alone (27 GB) dwarf a 16 GB
            # chip; bf16 halves both params and grads
            param_dtype=jnp.bfloat16)
        tx = optax.chain(optax.scale_by_factored_rms(),
                         optax.scale(-3e-4))
        mesh = create_mesh(MeshSpec(), jax.devices()[:1])
        micro, seq = 1, 2048
        sample = jnp.zeros((micro, seq), jnp.int32)
        trainer = build_trainer(
            Llama(cfg), tx, mesh, sample, cross_entropy_loss,
            accum_steps=1, micro_batch=micro, offload_opt_state=True,
        )
        state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (micro, seq),
                              dtype=np.int32)
        tok, tgt = trainer.shard_batch(tokens, tokens)
        steps = 5
        _, dt, _, _ = _timed_loop(trainer.step, state, tok, tgt,
                                  warmup=2, steps=steps)
        tokens_per_sec = micro * seq * steps / dt
        mfu = (tokens_per_sec * _model_flops_per_token(cfg, seq)
               / peak_flops(jax.devices()[0]))
        print(json.dumps({"tokens_per_sec": round(tokens_per_sec, 1),
                          "mfu": round(mfu, 4)}))
        return 0
    except Exception as e:
        return _oom_report(e)


def run_7b_bench(timeout_s: float = 1800.0) -> dict:
    """Run the --llama7b attempt in its own process (it must own the
    TPU; a failure must not kill the headline bench). The budget is 2x
    the old single-attempt 900 s: a micro-2 attempt that fails late
    (post-compile) plus the full micro-1 fallback is two on-chip
    compiles and two timed runs, each bounded by the old worst case."""
    return _run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--llama7b"],
        timeout_s)


def _measure() -> dict:
    """The headline measurement (owns the accelerator in THIS process)."""
    on_tpu = jax.default_backend() == "tpu"
    # Factored second moments (adafactor family) keep the optimizer
    # state out of HBM so the chip fits a model big enough to saturate
    # the MXU; the optimizer name goes in the metric label. Default
    # "factored_rms" is the adafactor core (scale_by_factored_rms) minus
    # the update-clipping/relative-step passes, which cost ~11 ms/step
    # of pure elementwise HBM traffic (measured 0.689 vs 0.662 MFU).
    # BENCH_OPT=adafactor runs the full optax.adafactor; BENCH_OPT=adamw
    # reverts to the fp32-Adam-sized configs (smaller model, same chip).
    opt_name = os.environ.get("BENCH_OPT", "factored_rms" if on_tpu
                              else "adamw")
    if on_tpu:
        # Model sized by HBM and optimizer state. adafactor (≈0 B/param
        # state; bf16 params + grads = 4 B/param): measured on v5e-16GB,
        # llama_1b at micro 2 no-remat is the MFU sweet spot — 0.63 vs
        # 0.49 for the adamw-sized 0.4B config (bigger matmuls at hidden
        # 2048; micro 4 drops to 0.57 from HBM pressure, a 2.4B config to
        # 0.54 from weight streaming). adamw (~16 B/param fp32 state)
        # needs the next size down at each tier.
        hbm = (jax.devices()[0].memory_stats() or {}).get(
            "bytes_limit", 16 << 30)
        lean = opt_name in ("adafactor", "factored_rms")
        if hbm > 60 << 30:        # v5p-95GB
            size, micro = (LlamaConfig.llama_7b, 2) if lean else (
                LlamaConfig.llama_1b, 8)
        elif hbm > 24 << 30:      # v4-32GB
            size, micro = (LlamaConfig.llama_1b, 4) if lean else (
                LlamaConfig.llama_410m, 8)
        else:                     # v5e/v5lite-16GB
            size, micro = (LlamaConfig.llama_wide_1b, 2) if lean else (
                LlamaConfig.llama_410m, 8)
        remat = os.environ.get("BENCH_REMAT", "0") == "1"
        cfg = size(max_seq_len=2048, attn_impl="flash", remat=remat,
                   embed_impl="gather", norm_impl="fused",
                   dtype=jnp.bfloat16)
        seq, steps, warmup = 2048, 10, 2
    else:
        cfg = LlamaConfig.tiny(attn_impl="reference")
        micro, seq, steps, warmup = 2, 64, 3, 1
    micro = int(os.environ.get("BENCH_MICRO_BATCH", micro))
    seq = int(os.environ.get("BENCH_SEQ", seq))

    mesh = create_mesh(MeshSpec(), jax.devices()[:1])
    model = Llama(cfg)
    if opt_name == "factored_rms":
        tx = optax.chain(optax.scale_by_factored_rms(),
                         optax.scale(-3e-4))
    elif opt_name == "adafactor":
        tx = optax.adafactor(3e-4)
    else:
        tx = optax.adamw(3e-4, weight_decay=0.1)
    sample = jnp.zeros((micro, seq), jnp.int32)
    trainer = build_trainer(
        model, tx, mesh, sample, cross_entropy_loss,
        accum_steps=1, micro_batch=micro,
    )
    state = trainer.init(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (micro, seq), dtype=np.int32)
    targets = rng.integers(0, cfg.vocab_size, (micro, seq), dtype=np.int32)
    tok, tgt = trainer.shard_batch(tokens, targets)

    per_step: list = []
    _, dt, warmup_loss, final_loss = _timed_loop(
        trainer.step, state, tok, tgt, warmup=warmup, steps=steps,
        per_step=per_step)
    assert final_loss == final_loss, "NaN loss"
    if final_loss >= warmup_loss:
        # a ~10-step window on synthetic data is noisy; a non-descending
        # loss is a warning, not a bench-killing failure
        print(f"WARNING: loss did not descend over the timed window "
              f"({warmup_loss} -> {final_loss})", file=sys.stderr)

    tokens_per_sec = micro * seq * steps / dt
    mfu = (tokens_per_sec * _model_flops_per_token(cfg, seq)
           / peak_flops(jax.devices()[0]))
    return {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(mfu, 4),
        "params_b": round(cfg.param_count() / 1e9, 2),
        "seq": seq,
        "opt": opt_name,
        "on_tpu": on_tpu,
        "critical_path": _critical_path_summary(per_step),
    }


def measure_main() -> int:
    """--measure subprocess: the headline measurement, isolated so a
    later TPU-owning phase (at-scale restore, 7B attempt) that wedges
    the tunnel can never take the headline metric down with it."""
    from dlrover_tpu.agent.elastic_agent import apply_jax_platform_env

    apply_jax_platform_env()
    print(json.dumps(_measure()))
    return 0


def run_measure_bench(timeout_s: float = 900.0) -> dict:
    return _run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--measure"],
        timeout_s)


def main() -> None:
    from dlrover_tpu.agent.elastic_agent import apply_jax_platform_env

    apply_jax_platform_env()   # JAX_PLATFORMS=cpu must win on dev machines
    skip_restore = os.environ.get("BENCH_SKIP_RESTORE") == "1"
    restore_result = {} if skip_restore else run_restore_bench()
    restore_s = -1.0 if skip_restore else _restore_seconds(restore_result)
    tpu_unreachable = False
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and not probe_tpu():
        # wedged tunnel: degrade to CPU so the bench reports instead of
        # hanging the driver
        tpu_unreachable = True
        jax.config.update("jax_platforms", "cpu")
    want_tpu = (os.environ.get("JAX_PLATFORMS", "") != "cpu"
                and not tpu_unreachable)
    restore_scale_s = -1.0
    restore_scale_result: dict = {}
    llama7b: dict = {}
    if want_tpu:
        # every TPU phase runs in its OWN subprocess (the tunnel serves
        # one client at a time), headline FIRST — later riskier phases
        # re-probe and are skipped if the tunnel wedged
        headline = run_measure_bench()
        if "error" in headline:
            tpu_unreachable = True
            jax.config.update("jax_platforms", "cpu")
            headline = _measure()
        # the at-scale restore and 7B phases are TPU-only: on a dev
        # machine the headline subprocess reports on_tpu=False (probing
        # devices alone can't tell — CPU devices probe fine)
        if headline.get("on_tpu"):
            if not skip_restore and probe_tpu():
                restore_scale_result = run_restore_bench(
                    timeout_s=900.0, at_scale=True)
                restore_scale_s = _restore_seconds(restore_scale_result)
            if os.environ.get("BENCH_SKIP_7B") != "1":
                if probe_tpu():
                    llama7b = run_7b_bench()
                else:
                    llama7b = {"error": "tunnel unreachable after "
                                        "earlier phase"}
    else:
        headline = _measure()   # CPU fallback, in-process

    tokens_per_sec = headline["tokens_per_sec"]
    mfu = headline["mfu"]
    result = {
        "metric": "llama_tokens_per_sec_per_chip",
        "value": tokens_per_sec,
        "unit": f"tokens/s ({headline['params_b']:.2f}B params, "
                f"seq {headline['seq']}, {headline['opt']}, "
                f"MFU {mfu:.3f}, "
                + (f"elastic_restore {restore_s:.1f}s vs <30s target)"
                   if restore_s >= 0 else "elastic_restore skipped)"),
        "vs_baseline": round(mfu / 0.40, 3),
        "elastic_restore_seconds": restore_s,
        "elastic_restore_seconds_at_scale": restore_scale_s,
    }
    if headline.get("critical_path"):
        result["critical_path"] = headline["critical_path"]
    # the at-scale restore is the number the <30 s target is about:
    # its breakdown wins when both ran
    _fold_restore_fields(result, restore_result)
    if restore_scale_result.get("breakdown"):
        _fold_restore_fields(result, restore_scale_result)
    if llama7b:
        result["llama7b_tokens_per_sec_per_chip"] = llama7b.get(
            "tokens_per_sec", -1.0)
        if "mfu" in llama7b:
            result["llama7b_mfu"] = llama7b["mfu"]
        if "micro_batch" in llama7b:
            # a micro-1 value here means the micro-2 default fell back —
            # visible in the scoreboard, not just the subprocess log
            result["llama7b_micro_batch"] = llama7b["micro_batch"]
        notes = [llama7b[k] for k in ("error", "fallback_note")
                 if k in llama7b]
        if notes:   # both attempts failing keeps BOTH reasons visible
            result["llama7b_note"] = " | ".join(notes)
    if tpu_unreachable:
        result["tpu_unreachable"] = True
        result["unit"] += " [TPU tunnel unreachable: CPU fallback]"
    print(json.dumps(result))


if __name__ == "__main__":
    if "--llama7b" in sys.argv:
        raise SystemExit(seven_b_main())
    if "--measure" in sys.argv:
        raise SystemExit(measure_main())
    main()
