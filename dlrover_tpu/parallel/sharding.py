"""Logical-axis → mesh-axis sharding rules.

Capability parity: the reference's per-strategy module surgery (Megatron
col/row-parallel classes layers.py:239-670, FSDP wrapping
zero_optimization.py:215, MIP graph-sharding planners) collapses into ONE
table: model params carry logical names (embed/heads/kv/mlp/vocab/norm) and
these rules decide which mesh axis each maps to. Changing the strategy is
changing the table — the model code never changes.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis

# (logical axis, mesh axis or None). Megatron mapping: column-parallel
# weights shard their output dim ("heads"/"mlp"/"vocab" → tensor), row-
# parallel shard their input dim; FSDP shards the long "embed" dim.
DEFAULT_RULES: List[Tuple[str, Optional[Any]]] = [
    ("vocab", MeshAxis.TENSOR),
    ("heads", MeshAxis.TENSOR),
    ("kv", MeshAxis.TENSOR),
    ("mlp", MeshAxis.TENSOR),
    ("embed", MeshAxis.FSDP),
    ("expert", MeshAxis.EXPERT),
    ("norm", None),
    # activation layout (consumed by nn.with_logical_constraint in the
    # models): batch over the joint dp axes — cross-slice dcn replicas
    # first, then data/fsdp within the slice (dcn is size 1 on
    # single-slice meshes, so the extra name is a no-op there); seq/
    # embed unsharded by default (the sequence axis claims act_seq
    # under SP)
    ("act_batch", (MeshAxis.DCN, MeshAxis.DATA, MeshAxis.FSDP)),
    ("act_seq", MeshAxis.SEQUENCE),
    ("act_embed", None),
]


def make_sharding_rules(
    fsdp: bool = True,
    tensor: bool = True,
    extra: Sequence[Tuple[str, Optional[str]]] = (),
) -> List[Tuple[str, Optional[Any]]]:
    rules = []
    for logical, axis in DEFAULT_RULES:
        if axis == MeshAxis.TENSOR and not tensor:
            axis = None
        if axis == MeshAxis.FSDP and not fsdp:
            axis = None
        rules.append((logical, axis))
    rules.extend(extra)
    return rules


def mesh_shardings(tree: Any, mesh: Mesh,
                   rules: Optional[Sequence[Tuple[str, Any]]] = None) -> Any:
    """Variables/abstract pytree (with nn.Partitioned annotations) →
    matching tree of NamedSharding."""
    rules = list(rules if rules is not None else DEFAULT_RULES)
    logical_specs = nn.get_partition_spec(tree)
    return nn.logical_to_mesh_sharding(logical_specs, mesh, rules)


def sanitize_shardings(shardings: Any, abstract: Any, mesh: Mesh) -> Any:
    """Replace shardings that cannot apply to their leaf's rank.

    Optimizer transformations can carry a param's logical axis names onto
    state leaves of DIFFERENT rank — e.g. adafactor's factored second
    moments are rank-1 reductions of rank-2 params, so the inherited
    2-axis spec is invalid for them. Any NamedSharding with more
    partitioned dims than the leaf has axes falls back to replicated
    (factored/statistic leaves are small; replication is the right call).
    `abstract` must be the UNBOXED abstract tree matching `shardings`.
    """
    def fix(s, a):
        if (isinstance(s, NamedSharding)
                and len(s.spec) > getattr(a, "ndim", 0)):
            return NamedSharding(mesh, P(), memory_kind=s.memory_kind)
        return s

    return jax.tree.map(fix, shardings, abstract)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global-batch arrays sharded over the joint dp axes
    (dcn + data + fsdp; dcn absent on pre-hierarchical meshes)."""
    from dlrover_tpu.parallel.mesh import data_axes

    return NamedSharding(mesh, P(data_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def unbox(tree: Any) -> Any:
    """Strip nn.Partitioned boxes (for code that wants raw arrays)."""
    return nn.unbox(tree)


def sharded_from_host(host_tree: Any, abstract_tree: Any) -> Any:
    """Host buffers → global arrays in the abstract tree's shardings.

    The resharding primitive behind peer-to-peer restore (and the
    starting point for online parallelism re-planning): each process
    materializes only its addressable shards via
    ``jax.make_array_from_callback``, so a full-replica host buffer
    lands as a sharded/replicated device array without a second full
    copy per device, on one host or many."""
    def put(host_leaf, abstract_leaf):
        sharding = getattr(abstract_leaf, "sharding", None)
        if isinstance(host_leaf, jax.Array):
            # already on device (e.g. the mixed-restore Orbax overlay):
            # reshard in place — never a host round-trip
            return (host_leaf if sharding is None
                    else jax.device_put(host_leaf, sharding))
        arr = np.asarray(host_leaf)
        if sharding is None:
            return jax.device_put(arr)
        return jax.make_array_from_callback(
            tuple(arr.shape), sharding, lambda idx: arr[idx])

    return jax.tree.map(put, host_tree, abstract_tree)


def reshard(tree: Any, shardings: Any) -> Any:
    """Live device arrays → new shardings (a resize-time state
    migration: the collective moves shards instead of a checkpoint
    round-trip)."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree,
                        shardings)
