"""Planner prediction ↔ measurement calibration: close the loop.

PR 9's planner emits a ``predicted_step_s`` for every stamped plan and
nothing ever checked it against measurement — the per-axis efficiency
penalties in ``parallel/planner.py`` are an analytic prior, and a prior
that is never confronted with data quietly mis-ranks meshes forever.
:class:`PlanCalibration` is the confrontation: per applied shard-plan
SIGNATURE (mesh + device count + batch — the execution shape) it
records the planner's prediction and accumulates the steady-state
measured step time / MFU the workers' step reports carry (already
windowed means from the phase timeline, so each sample is steady-state
evidence, not a single noisy step). From the table it derives learned
per-axis efficiency discounts the rendezvous managers feed back into
planner scoring (``set_axis_discounts``), and the current signature's
predicted-vs-measured ratio is the :class:`~dlrover_tpu.master.
diagnosis.rules.PlanRegressionRule`'s evidence.

stdlib-only (the jax-free master owns it), thread-safe (fed from
servicer threads, read by the diagnosis loop / RPC / tools), exported
and restored through the PR 3 state backend so calibration survives a
master failover or standby promotion — re-learning the fleet's real
efficiency from scratch after every control-plane event would defeat
the point.
"""

from __future__ import annotations

import json
import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

CALIBRATION_VERSION = 1

# samples retained per signature (each already a windowed worker mean)
SAMPLE_WINDOW = 64
# learned discounts are clamped: calibration refines the prior, it must
# never be able to zero an axis out (or inflate it) off noisy evidence
DISCOUNT_MIN = 0.25
DISCOUNT_MAX = 2.0
# axes a discount can be learned for (mesh dict keys, planner order)
AXES = ("dcn", "data", "fsdp", "tensor", "pipe")


def plan_signature(plan: Dict[str, Any]) -> str:
    """The execution shape as a stable string — the calibration key.
    Mesh + device count + effective batch: what the step time actually
    depends on (generation/epoch deliberately excluded: a re-stamp of
    the same shape continues the same measurement series)."""
    return json.dumps({
        "mesh": {k: int((plan.get("mesh") or {}).get(k, 1))
                 for k in AXES},
        "total_devices": int(plan.get("total_devices", 0) or 0),
        "global_batch": int(plan.get("global_batch", 0) or 0),
    }, sort_keys=True, separators=(",", ":"))


class PlanCalibration:
    def __init__(self, sample_window: int = SAMPLE_WINDOW,
                 min_samples: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        from dlrover_tpu.common.config import Context

        self._window = max(2, int(sample_window))
        self._min_samples = (
            min_samples if min_samples is not None
            else Context.singleton().calibration_min_samples)
        self._clock = clock
        self._lock = threading.Lock()
        # signature -> {"mesh", "total_devices", "global_batch",
        #   "predicted_step_s", "predicted_efficiency", "generation",
        #   "first_ts", "samples": deque[(step_s, mfu)]}
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._current: Optional[str] = None
        # latest stamped generation -> signature (each generation
        # stamps exactly one plan): the attribution key for reports
        # that say which plan their sender actually ran
        self._by_generation: Dict[int, str] = {}

    @property
    def min_samples(self) -> int:
        return self._min_samples

    # -- feeds (servicer threads) ------------------------------------------
    def observe_plan(self, plan: Dict[str, Any]) -> None:
        """A plan was stamped (or re-stamped) by the master: remember
        its prediction under its signature and make it the CURRENT
        shape measurements attribute to. Infeasible plans are not
        calibration subjects — nothing runs them."""
        if not isinstance(plan, dict) or not plan.get("mesh") \
                or not plan.get("feasible", False):
            return
        signature = plan_signature(plan)
        predicted = float(plan.get("predicted_step_s", 0.0) or 0.0)
        # the stamped prediction already includes the learned discounts
        # (planner._efficiency): calibrating against it would measure
        # the correction against its own output — each push re-stamps
        # a compensated prediction, the ratio re-centers on 1.0, the
        # discount decays and oscillates. Divide the plan's stamped
        # discounts back out so the learned ratio stays anchored to
        # the RAW analytic prior (step time scales 1/efficiency, so
        # raw = discounted x the active axes' discount product).
        stamped = plan.get("axis_discounts") or {}
        if predicted > 0.0 and stamped:
            for axis in AXES:
                ways = int((plan.get("mesh") or {}).get(axis, 1) or 1)
                discount = float(stamped.get(axis, 0.0) or 0.0)
                if ways > 1 and discount > 0.0:
                    predicted *= discount
        with self._lock:
            entry = self._entries.get(signature)
            if entry is None:
                entry = {
                    "mesh": {k: int(plan["mesh"].get(k, 1))
                             for k in AXES},
                    "total_devices": int(
                        plan.get("total_devices", 0) or 0),
                    "global_batch": int(
                        plan.get("global_batch", 0) or 0),
                    "first_ts": self._clock(),
                    "samples": deque(maxlen=self._window),
                }
                self._entries[signature] = entry
            entry["predicted_step_s"] = predicted
            entry["predicted_efficiency"] = float(
                plan.get("predicted_efficiency", 0.0) or 0.0)
            entry["generation"] = int(plan.get("generation", 0) or 0)
            self._by_generation[entry["generation"]] = signature
            # bounded: a flapping fleet bumps generations forever, but
            # only recent ones can still have in-flight reports
            while len(self._by_generation) > 256:
                self._by_generation.pop(min(self._by_generation))
            self._current = signature

    def observe_step(self, step_time_s: float, mfu: float = -1.0,
                     plan_generation: int = -1) -> None:
        """One steady-state measurement (a worker's windowed mean step
        time, optionally its achieved MFU). A measurement must never
        land on a shape it did not run: when the report names the plan
        generation its sender applied (``plan_generation >= 0``) the
        sample lands on THAT stamped shape — so an old incarnation's
        straggling report during a resize cannot contaminate the new
        plan's entry — and a report from a fallback-mesh worker
        (``-2``) is dropped. ``-1`` (sender predates the field) keeps
        the current-signature attribution; no current plan → no
        attribution."""
        if step_time_s <= 0.0:
            return
        with self._lock:
            if plan_generation >= 0:
                signature = self._by_generation.get(plan_generation)
            elif plan_generation == -1:
                signature = self._current
            else:                      # explicit "not the stamped plan"
                signature = None
            entry = (self._entries.get(signature)
                     if signature else None)
            if entry is None:
                return
            entry["samples"].append((float(step_time_s), float(mfu)))

    # -- views -------------------------------------------------------------
    def _entry_view_locked(self, signature: str,
                           entry: Dict[str, Any]) -> Dict[str, Any]:
        samples = list(entry["samples"])
        times = [t for t, _ in samples]
        mfus = [m for _, m in samples if m >= 0.0]
        measured = sum(times) / len(times) if times else 0.0
        predicted = float(entry.get("predicted_step_s", 0.0))
        return {
            "signature": signature,
            "mesh": dict(entry["mesh"]),
            "total_devices": entry["total_devices"],
            "global_batch": entry["global_batch"],
            "generation": entry.get("generation", 0),
            "predicted_step_s": round(predicted, 9),
            "predicted_efficiency": round(
                float(entry.get("predicted_efficiency", 0.0)), 4),
            "measured_step_s": round(measured, 9),
            "measured_mfu": round(sum(mfus) / len(mfus), 4)
            if mfus else -1.0,
            "samples": len(samples),
            "ratio": round(measured / predicted, 4)
            if predicted > 0 and measured > 0 else 0.0,
            "current": signature == self._current,
        }

    def current(self) -> Optional[Dict[str, Any]]:
        """The running shape's predicted-vs-measured entry (the
        PlanRegressionRule's evidence); None before any plan."""
        with self._lock:
            if not self._current:
                return None
            entry = self._entries.get(self._current)
            if entry is None:
                return None
            return self._entry_view_locked(self._current, entry)

    def table(self) -> List[Dict[str, Any]]:
        """Every calibrated shape, stamped-first order (by first_ts):
        what ``bench_replan.py`` emits and ``tools/top.py`` renders."""
        with self._lock:
            ordered = sorted(self._entries.items(),
                             key=lambda kv: kv[1].get("first_ts", 0.0))
            return [self._entry_view_locked(sig, entry)
                    for sig, entry in ordered]

    # -- the feedback loop -------------------------------------------------
    def axis_discounts(self,
                       min_samples: Optional[int] = None
                       ) -> Dict[str, float]:
        """Learned per-axis efficiency discounts for planner scoring.

        For each mesh axis: the median predicted/measured speed ratio
        of shapes USING the axis (size > 1), normalized by the median
        ratio of shapes NOT using it — so a global model bias (every
        shape 20 % slower than predicted) cancels instead of being
        blamed on whichever axis happens to be active. Clamped to
        [0.25, 2.0]; axes with no adequately-sampled evidence on both
        sides learn nothing (empty dict = prior stands)."""
        threshold = (min_samples if min_samples is not None
                     else self._min_samples)
        with self._lock:
            ratios = []        # (mesh, predicted/measured)
            for entry in self._entries.values():
                samples = [t for t, _ in entry["samples"]]
                predicted = float(entry.get("predicted_step_s", 0.0))
                if len(samples) < threshold or predicted <= 0.0:
                    continue
                measured = sum(samples) / len(samples)
                if measured <= 0.0:
                    continue
                ratios.append((entry["mesh"], predicted / measured))
        discounts: Dict[str, float] = {}
        for axis in AXES:
            with_axis = [r for mesh, r in ratios
                         if int(mesh.get(axis, 1)) > 1]
            without = [r for mesh, r in ratios
                       if int(mesh.get(axis, 1)) <= 1]
            if not with_axis or not without:
                continue
            baseline = statistics.median(without)
            if baseline <= 0.0:
                continue
            learned = statistics.median(with_axis) / baseline
            discounts[axis] = round(
                min(DISCOUNT_MAX, max(DISCOUNT_MIN, learned)), 4)
        return discounts

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "version": CALIBRATION_VERSION,
                "current": self._current or "",
                "entries": {
                    sig: {
                        "mesh": dict(entry["mesh"]),
                        "total_devices": entry["total_devices"],
                        "global_batch": entry["global_batch"],
                        "generation": entry.get("generation", 0),
                        "first_ts": entry.get("first_ts", 0.0),
                        "predicted_step_s": entry.get(
                            "predicted_step_s", 0.0),
                        "predicted_efficiency": entry.get(
                            "predicted_efficiency", 0.0),
                        "samples": [[t, m] for t, m
                                    in entry["samples"]],
                    }
                    for sig, entry in self._entries.items()
                },
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        if not isinstance(state, dict):
            return
        with self._lock:
            self._entries.clear()
            self._by_generation.clear()
            for sig, raw in (state.get("entries") or {}).items():
                if not isinstance(raw, dict):
                    continue
                samples: deque = deque(maxlen=self._window)
                for pair in raw.get("samples", []):
                    if isinstance(pair, (list, tuple)) \
                            and len(pair) == 2:
                        samples.append((float(pair[0]),
                                        float(pair[1])))
                self._entries[str(sig)] = {
                    "mesh": {k: int((raw.get("mesh") or {}).get(k, 1))
                             for k in AXES},
                    "total_devices": int(
                        raw.get("total_devices", 0) or 0),
                    "global_batch": int(
                        raw.get("global_batch", 0) or 0),
                    "generation": int(raw.get("generation", 0) or 0),
                    "first_ts": float(raw.get("first_ts", 0.0) or 0.0),
                    "predicted_step_s": float(
                        raw.get("predicted_step_s", 0.0) or 0.0),
                    "predicted_efficiency": float(
                        raw.get("predicted_efficiency", 0.0) or 0.0),
                    "samples": samples,
                }
                self._by_generation[
                    self._entries[str(sig)]["generation"]] = str(sig)
            current = str(state.get("current", "") or "")
            self._current = current if current in self._entries else None
