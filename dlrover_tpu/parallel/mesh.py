"""Named-axis mesh construction.

Capability parity: atorch `create_parallel_group(([("tensor",4),("pipe",2),
("data",2)], None))` (atorch/distributed/distributed.py:323-334) — the same
named-dims spec builds a `jax.sharding.Mesh` instead of torch process
groups. Axis order follows the spec; put the fastest-varying (innermost ICI)
axis last — conventionally `tensor` — so tensor-parallel collectives ride
the tightest ICI loops.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from dlrover_tpu.common.constants import MeshAxis

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes of each named parallel dim; 1 = unused. data is inferred when
    left at 0 (elastic: it absorbs whatever devices remain).

    ``dcn`` is the explicit hierarchical axis for multi-slice jobs: one
    mesh coordinate per ICI slice, placed OUTERMOST so every other axis
    stays inside a slice. Gradient sync then runs hierarchically —
    in-slice reduce over ICI (data/fsdp), cross-slice (all-)reduce over
    ``dcn`` (see trainer/train_step.py and
    parallel/quant_collectives.py)."""

    data: int = 0
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1
    dcn: int = 1

    def with_total_devices(self, n_devices: int) -> "MeshSpec":
        fixed = (self.fsdp * self.tensor * self.sequence * self.expert
                 * self.pipe * self.dcn)
        if self.data:
            if self.data * fixed != n_devices:
                raise ValueError(
                    f"mesh spec {self} needs {self.data * fixed} devices, "
                    f"got {n_devices}"
                )
            return self
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed dims {fixed}"
            )
        return dataclasses.replace(self, data=n_devices // fixed)

    def axis_sizes(self) -> List[Tuple[str, int]]:
        return [
            (MeshAxis.DCN, self.dcn),
            (MeshAxis.DATA, self.data or 1),
            (MeshAxis.FSDP, self.fsdp),
            (MeshAxis.PIPE, self.pipe),
            (MeshAxis.EXPERT, self.expert),
            (MeshAxis.SEQUENCE, self.sequence),
            (MeshAxis.TENSOR, self.tensor),
        ]

    @property
    def total(self) -> int:
        return math.prod(size for _, size in self.axis_sizes())

    @classmethod
    def from_pairs(cls, pairs: Sequence[Tuple[str, int]]) -> "MeshSpec":
        """atorch-style [("data",2),("tensor",4)]."""
        sizes: Dict[str, int] = {}
        for name, size in pairs:
            if name not in MeshAxis.ALL:
                raise ValueError(f"unknown mesh axis {name!r}; "
                                 f"choose from {MeshAxis.ALL}")
            sizes[name] = sizes.get(name, 1) * size
        return cls(**sizes)


def dcn_granules(devices) -> Tuple[int, bool]:
    """(number of DCN granules, granule-is-process). Granules are SLICES
    when the platform reports them (a multi-host single-slice pod is
    all-ICI: plain topology assignment is correct there); otherwise each
    process is its own DCN domain (CPU meshes, non-slice platforms).
    Single source of the rule — the auto-planner's multi-slice detection
    (auto/engine/analyser.py) must agree with the mesh it plans for."""
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None in slice_ids:
        return len({getattr(d, "process_index", 0) for d in devices}), True
    return len(slice_ids), False


def _dcn_split(spec: MeshSpec, n_granules: int) -> Optional[List[int]]:
    """Split one mesh axis across the slow (DCN) fabric.

    Returns the per-axis DCN shape (same order as ``axis_sizes``), or
    None when no single axis divides evenly by the granule count.
    An explicit hierarchical spec (``dcn > 1``) pins the split to the
    dcn axis — that axis exists precisely to carry the cross-slice
    dimension. Otherwise preference order: data, then pipe, then fsdp —
    gradient all-reduce over data tolerates DCN latency best (it
    overlaps with backward), pipe crosses the fabric once per
    microbatch boundary, while tensor/sequence/expert collectives are
    latency-bound and must stay on ICI (SURVEY §2.5)."""
    sizes = spec.axis_sizes()
    dcn = [1] * len(sizes)
    if spec.dcn > 1:
        idx = next(i for i, (name, _) in enumerate(sizes)
                   if name == MeshAxis.DCN)
        if spec.dcn % n_granules == 0:
            dcn[idx] = n_granules
            return dcn
        return None
    preference = (MeshAxis.DATA, MeshAxis.PIPE, MeshAxis.FSDP)
    for axis in preference:
        idx = next(i for i, (name, _) in enumerate(sizes) if name == axis)
        if sizes[idx][1] % n_granules == 0:
            dcn[idx] = n_granules
            return dcn
    return None


def create_mesh(spec: Optional[MeshSpec] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the mesh, topology-aware. All axes always exist (size 1 when
    unused) so partition specs never have to special-case a missing axis.

    Device→coordinate assignment goes through
    ``mesh_utils.create_device_mesh`` so mesh axes map onto contiguous
    ICI rings/tori of the physical TPU topology (the reference plans
    groups over the physical fabric the same way:
    atorch/auto/opt_lib/shard_planners/mip_tp_planner.py:30 + NCCL's
    topology detection). Multi-process jobs spanning slices get a hybrid
    ICI×DCN mesh with the data (or pipe) axis across the slow fabric.
    Falls back to a row-major reshape for device subsets or shapes the
    topology solver rejects (CPU test meshes, partial-chip benches)."""
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).with_total_devices(len(devices))
    names = tuple(name for name, _ in spec.axis_sizes())
    shape = tuple(size for _, size in spec.axis_sizes())

    n_granules, process_is_granule = dcn_granules(devices)
    array: Optional[np.ndarray] = None
    if n_granules > 1:
        dcn_shape = _dcn_split(spec, n_granules)
        if dcn_shape is None:
            logger.warning(
                "mesh spec %s has no axis divisible by %d DCN granules; "
                "falling back to granule-major reshape — cross-DCN "
                "collectives on fast axes will be slow", spec, n_granules)
        else:
            per_granule = tuple(s // d for s, d in zip(shape, dcn_shape))
            try:
                array = mesh_utils.create_hybrid_device_mesh(
                    per_granule, dcn_shape, devices=devices,
                    process_is_granule=process_is_granule,
                    allow_split_physical_axes=True)
            except (ValueError, NotImplementedError, AssertionError) as e:
                logger.warning("hybrid device mesh failed (%s); "
                               "falling back to reshape", e)
    else:
        try:
            array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True)
        except (ValueError, NotImplementedError, AssertionError) as e:
            # Subsets of a slice (bench on 1 of N chips) and CPU test
            # meshes have no topology to exploit — row-major is correct
            # there; on a full slice this path never triggers.
            logger.debug("topology mesh assignment unavailable (%s); "
                         "using row-major order", e)
    if array is None:
        array = np.asarray(devices).reshape(shape)
    return Mesh(array, names)


# Ambient-mesh context: an explicit, public alternative to reading
# jax's private thread_resources. build_trainer (and anything tracing
# model code) enters use_mesh() so ring/Ulysses attention can reach the
# concrete mesh for their inner shard_map at trace time without the
# model carrying the mesh through its config.
_AMBIENT_MESH: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("dlrover_tpu_ambient_mesh", default=None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Enter ``mesh`` as the ambient mesh (also enters jax's own mesh
    context so flax logical-axis machinery sees it)."""
    token = _AMBIENT_MESH.set(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _AMBIENT_MESH.reset(token)


def current_mesh() -> Optional[Mesh]:
    """The ambient mesh set by :func:`use_mesh`, falling back to jax's
    own mesh context (a bare ``with mesh:``) so external callers using
    the documented jax idiom still get sequence-parallel dispatch and
    the flash-attention shard_map wrapper."""
    mesh = _AMBIENT_MESH.get()
    if mesh is not None:
        return mesh
    try:
        # A bare `with mesh:` registers only in jax's thread resources;
        # read them defensively — the attribute is not public API, and
        # losing the fallback on a jax upgrade must degrade to "no
        # ambient mesh", not crash.
        from jax._src import mesh as _jax_mesh  # noqa: PLC0415

        ambient = _jax_mesh.thread_resources.env.physical_mesh
        if ambient is not None and not ambient.empty:
            return ambient
    except Exception:
        pass
    return None


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes the batch dim is sharded over (dcn + data + fsdp jointly:
    cross-slice replicas over the DCN axis, then the standard ZeRO-3
    data+fsdp layout within a slice). Meshes without a dcn axis (built
    before the hierarchical spec) keep the old pair."""
    if MeshAxis.DCN in mesh.shape:
        return (MeshAxis.DCN, MeshAxis.DATA, MeshAxis.FSDP)
    return (MeshAxis.DATA, MeshAxis.FSDP)


def dp_size(mesh: Mesh) -> int:
    return (mesh.shape.get(MeshAxis.DCN, 1)
            * mesh.shape[MeshAxis.DATA] * mesh.shape[MeshAxis.FSDP])


def dcn_size(mesh: Mesh) -> int:
    """Slices the mesh spans (1 = single-slice / pre-hierarchical)."""
    return mesh.shape.get(MeshAxis.DCN, 1)
