"""Named-axis mesh construction.

Capability parity: atorch `create_parallel_group(([("tensor",4),("pipe",2),
("data",2)], None))` (atorch/distributed/distributed.py:323-334) — the same
named-dims spec builds a `jax.sharding.Mesh` instead of torch process
groups. Axis order follows the spec; put the fastest-varying (innermost ICI)
axis last — conventionally `tensor` — so tensor-parallel collectives ride
the tightest ICI loops.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from dlrover_tpu.common.constants import MeshAxis


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes of each named parallel dim; 1 = unused. data is inferred when
    left at 0 (elastic: it absorbs whatever devices remain)."""

    data: int = 0
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1

    def with_total_devices(self, n_devices: int) -> "MeshSpec":
        fixed = (self.fsdp * self.tensor * self.sequence * self.expert
                 * self.pipe)
        if self.data:
            if self.data * fixed != n_devices:
                raise ValueError(
                    f"mesh spec {self} needs {self.data * fixed} devices, "
                    f"got {n_devices}"
                )
            return self
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed dims {fixed}"
            )
        return dataclasses.replace(self, data=n_devices // fixed)

    def axis_sizes(self) -> List[Tuple[str, int]]:
        return [
            (MeshAxis.DATA, self.data or 1),
            (MeshAxis.FSDP, self.fsdp),
            (MeshAxis.PIPE, self.pipe),
            (MeshAxis.EXPERT, self.expert),
            (MeshAxis.SEQUENCE, self.sequence),
            (MeshAxis.TENSOR, self.tensor),
        ]

    @property
    def total(self) -> int:
        return math.prod(size for _, size in self.axis_sizes())

    @classmethod
    def from_pairs(cls, pairs: Sequence[Tuple[str, int]]) -> "MeshSpec":
        """atorch-style [("data",2),("tensor",4)]."""
        sizes: Dict[str, int] = {}
        for name, size in pairs:
            if name not in MeshAxis.ALL:
                raise ValueError(f"unknown mesh axis {name!r}; "
                                 f"choose from {MeshAxis.ALL}")
            sizes[name] = sizes.get(name, 1) * size
        return cls(**sizes)


def create_mesh(spec: Optional[MeshSpec] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the mesh. All axes always exist (size 1 when unused) so
    partition specs never have to special-case a missing axis."""
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).with_total_devices(len(devices))
    names = tuple(name for name, _ in spec.axis_sizes())
    shape = tuple(size for _, size in spec.axis_sizes())
    array = np.asarray(devices).reshape(shape)
    return Mesh(array, names)


def current_mesh() -> Optional[Mesh]:
    """The ambient physical mesh (set by ``with mesh:``), or None.

    Model code that needs a concrete mesh for an inner ``shard_map``
    (ring/Ulysses attention) reads it from here at trace time —
    build_trainer enters the mesh context around tracing, so the model
    never has to carry the mesh through its config."""
    from jax._src import mesh as mesh_lib  # no public accessor yet

    physical = mesh_lib.thread_resources.env.physical_mesh
    if physical.devices.size:
        return physical
    return None


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes the batch dim is sharded over (data + fsdp jointly, the
    standard ZeRO-3 layout)."""
    return (MeshAxis.DATA, MeshAxis.FSDP)


def dp_size(mesh: Mesh) -> int:
    return (mesh.shape[MeshAxis.DATA] * mesh.shape[MeshAxis.FSDP])
