"""Sequence-parallel attention: ring attention + Ulysses all-to-all.

Capability parity: atorch DistributedSelfAttention
(atorch/modules/distributed_transformer/distributed_attention.py:21-115 —
seq-sharded K/V, micro-chunked Q all-gather, distributed online softmax via
global max/sum all-reduce, reduce-scatter of context, dual-stream overlap).

TPU re-design: the sequence dim is a mesh axis under `shard_map`.
- `ring_attention`: K/V blocks rotate around the ring with `ppermute`
  while each device keeps its Q shard; softmax is accumulated online
  (running max/sum) — numerically identical to blockwise/flash attention.
  Communication rides the ICI ring; compute of block i overlaps the
  permute of block i+1 because XLA schedules the independent DMA and
  matmul concurrently (the role of the reference's dual CUDA streams).
- `ulysses_attention`: `all_to_all` re-shards sequence→heads so every
  device runs dense attention on full sequences for its head group, then
  re-shards back (head-parallel SP; absent in the reference snapshot —
  noted in SURVEY.md §2.4).

The einsum paths are pure jax.lax collectives (autodiff derives the
backward; ppermute/all_to_all have transpose rules). The TPU-default
flash paths are NOT: the ring's is a custom VJP over Pallas kernels
(forward-mode AD unsupported there), and Ulysses calls the flash
kernel's own custom VJP.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.common.jax_compat import shard_map

_NEG_INF = -1e30


def _use_flash_blocks(block_impl: str) -> bool:
    """Per-device attention kernel dispatch shared by ring and Ulysses:
    "auto" = flash kernel on TPU, einsum elsewhere.
    $DLROVER_TPU_SP_BLOCK_IMPL overrides "auto" (tests force the flash
    path through the model-level product dispatch in interpret mode)."""
    import os

    if block_impl == "auto":
        # deliberate trace-time read: kernel dispatch is a per-lowering
        # decision and must re-resolve on every elastic re-trace
        env = "DLROVER_TPU_SP_BLOCK_IMPL"
        block_impl = os.environ.get(env, "auto")  # graftlint: disable=GL102
    block_impl = block_impl.strip().lower()
    if block_impl not in ("auto", "flash", "einsum"):
        raise ValueError(
            f"unknown SP block impl {block_impl!r}: "
            "expected auto | flash | einsum")
    return block_impl == "flash" or (
        block_impl == "auto" and jax.default_backend() == "tpu")


def _block_attn(q, k, v, scale, mask):
    """One Q-shard × KV-block: returns (unnorm_out, block_max, block_sum).

    q: (B, Lq, H, D), k/v: (B, Lk, H, D) (GQA callers repeat KV heads to H
    before this), mask: (Lq, Lk) additive or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = s + mask[None, None, :, :]
    m = jnp.max(s, axis=-1)                          # (B, H, Lq)
    # guard fully-masked rows (causal first block): exp(-inf - -inf)
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])               # (B, H, Lq, Lk)
    l = jnp.sum(p, axis=-1)                          # (B, H, Lq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def _online_merge(o, m, l, o_new, m_new, l_new):
    """Merge a new block into the running (o, m, l) accumulators."""
    m_next = jnp.maximum(m, m_new)
    alpha = jnp.exp(m - m_next)          # rescale old
    beta = jnp.exp(m_new - m_next)       # rescale new
    l_next = l * alpha + l_new * beta
    o_next = (o * alpha[..., None].transpose(0, 2, 1, 3)
              + o_new * beta[..., None].transpose(0, 2, 1, 3))
    return o_next, m_next, l_next


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool,
                     scale: float, block_impl: str = "auto"):
    """Per-device body under shard_map. q: (B, L_local, H, D); k/v may
    carry fewer (GQA) heads — only the small KV shards rotate around the
    ring; the head replication happens locally per block (einsum path)
    or inside the kernel's GQA index maps (flash path), so ppermute
    traffic is not multiplied by the group count.

    block_impl: "auto" (flash kernel on TPU, einsum elsewhere) |
    "flash" | "einsum"."""
    if _use_flash_blocks(block_impl):
        # kernel layout (B, H, L, D); custom-VJP ring-flash path
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        out = _ring_flash_local(qt, kt, vt, axis_name, causal, scale)
        return out.transpose(0, 2, 1, 3)
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    batch, l_local, heads, dim = q.shape
    groups = heads // k.shape[2]

    q32 = q.astype(jnp.float32)

    diag_mask = jnp.where(
        jnp.arange(l_local)[None, :] > jnp.arange(l_local)[:, None],
        _NEG_INF, 0.0).astype(jnp.float32)

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (my_idx - i) % axis_size
        if groups > 1:
            k_rep = jnp.repeat(k_blk, groups, axis=2)
            v_rep = jnp.repeat(v_blk, groups, axis=2)
        else:
            k_rep, v_rep = k_blk, v_blk

        def merge(mask):
            o_new, m_new, l_new = _block_attn(q32, k_rep, v_rep, scale,
                                              mask)
            return _online_merge(o, m, l, o_new, m_new, l_new)

        if causal:
            # Three block kinds per step: diagonal (causal mask), fully
            # visible past block (no mask), fully masked future block
            # (skipped — its softmax weight is exactly zero). The switch
            # predicate varies per device, which is fine here: this
            # shard_map is fully manual, so the branches are pure local
            # compute with no collectives to diverge on. Skipping future
            # blocks halves the causal ring's compute.
            branch = jnp.where(kv_idx == my_idx, 0,
                               jnp.where(kv_idx < my_idx, 1, 2))
            o, m, l = lax.switch(branch, [
                lambda _: merge(diag_mask),
                lambda _: merge(None),
                lambda _: (o, m, l),
            ], None)
        else:
            o, m, l = merge(None)
        # rotate K/V to the next device; the permute of step i+1 overlaps
        # this step's matmuls (independent DMA)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    o0 = jnp.zeros((batch, l_local, heads, dim), jnp.float32)
    m0 = jnp.full((batch, heads, l_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, heads, l_local), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size))
    denominator = l[..., None].transpose(0, 2, 1, 3)
    out = o / jnp.maximum(denominator, 1e-20)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ring attention on the flash kernel (MXU-rate blocks, O(L_local) memory)
# ---------------------------------------------------------------------------
#
# The einsum ring above materializes (L_local × L_local) block scores; the
# flash path runs the Pallas kernel per visiting KV block and merges the
# NORMALIZED per-block outputs via their logsumexp — the standard
# ring-flash construction. Autodiff cannot see through pallas_call, so the
# backward is a custom VJP: a second ring pass where each visiting KV
# block's (dk, dv) accumulator travels around the ring WITH the block and
# arrives home after S rotations; per-block grads come from the flash
# backward kernels evaluated with the FINAL global lse (which makes each
# block's softmax weights exact).


def _merge_normalized(o, lse, o_b, lse_b):
    """Merge (normalized out, lse) accumulators; -inf lse = empty."""
    lse_n = jnp.logaddexp(lse, lse_b)
    w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_n), 0.0)
    w_new = jnp.where(jnp.isfinite(lse_b), jnp.exp(lse_b - lse_n), 0.0)
    return o * w_old + o_b * w_new, lse_n


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale):
    """q (B,H,L,D), k/v (B,KV,L,D) kernel layout; returns (out, lse)."""
    from dlrover_tpu.ops.flash_attention import _flash_fwd

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    fwd_perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    batch, heads, l_local, dim = q.shape

    def block(flag):
        def run(kv):
            from dlrover_tpu.ops.flash_attention import (
                DEFAULT_BLOCK_K,
                DEFAULT_BLOCK_Q,
            )

            o_b, lse_b = _flash_fwd(q, kv[0], kv[1], scale, flag,
                                    DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
            return o_b.astype(jnp.float32), lse_b

        return run

    def step(carry, i):
        o, lse, kb, vb = carry
        kv_idx = (my_idx - i) % axis_size
        if causal:
            branch = jnp.where(kv_idx == my_idx, 0,
                               jnp.where(kv_idx < my_idx, 1, 2))
            o_b, lse_b = lax.switch(branch, [
                block(True),            # diagonal: causal mask
                block(False),           # fully visible past block
                lambda kv: (jnp.zeros(q.shape, jnp.float32),
                            jnp.full((batch, heads, l_local, 1),
                                     -jnp.inf, jnp.float32)),
            ], (kb, vb))
        else:
            o_b, lse_b = block(False)((kb, vb))
        o, lse = _merge_normalized(o, lse, o_b, lse_b)
        kb = lax.ppermute(kb, axis_name, fwd_perm)
        vb = lax.ppermute(vb, axis_name, fwd_perm)
        return (o, lse, kb, vb), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((batch, heads, l_local, 1), -jnp.inf, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v),
                                 jnp.arange(axis_size))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash_local(q, k, v, axis_name, causal, scale):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, g):
    from dlrover_tpu.ops.flash_attention import _flash_bwd

    q, k, v, out, lse = res
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    fwd_perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    # step-invariant: rowsum(dO·O), computed once for the whole ring
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def block(flag):
        def run(kv):
            from dlrover_tpu.ops.flash_attention import (
                DEFAULT_BLOCK_K,
                DEFAULT_BLOCK_Q,
            )

            dq_b, dk_b, dv_b = _flash_bwd(
                (q, kv[0], kv[1], out, lse), g, sm_scale=scale,
                causal=flag, block_q=DEFAULT_BLOCK_Q,
                block_k=DEFAULT_BLOCK_K, delta=delta)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        return run

    def zeros(kv):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(k.shape, jnp.float32),
                jnp.zeros(v.shape, jnp.float32))

    def step(carry, i):
        dq, kb, vb, dkb, dvb = carry
        kv_idx = (my_idx - i) % axis_size
        if causal:
            branch = jnp.where(kv_idx == my_idx, 0,
                               jnp.where(kv_idx < my_idx, 1, 2))
            dq_b, dk_b, dv_b = lax.switch(
                branch, [block(True), block(False), zeros], (kb, vb))
        else:
            dq_b, dk_b, dv_b = block(False)((kb, vb))
        dq = dq + dq_b
        dkb = dkb + dk_b
        dvb = dvb + dv_b
        # the (dk, dv) accumulators travel WITH their kv block; after
        # axis_size rotations both are back at the block's owner
        kb = lax.ppermute(kb, axis_name, fwd_perm)
        vb = lax.ppermute(vb, axis_name, fwd_perm)
        dkb = lax.ppermute(dkb, axis_name, fwd_perm)
        dvb = lax.ppermute(dvb, axis_name, fwd_perm)
        return (dq, kb, vb, dkb, dvb), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(axis_size))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = MeshAxis.SEQUENCE,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    batch_axes=(MeshAxis.DATA, MeshAxis.FSDP),
    head_axis: Optional[str] = MeshAxis.TENSOR,
    block_impl: str = "auto",
) -> jax.Array:
    """Full-array API: q (B, S, H, D), k/v (B, S, KV, D) with KV ≤ H (GQA),
    all sharded S over `axis`; returns the attention output with q's
    sharding. Composes with tensor parallelism (heads over `head_axis`)
    in one shard_map. block_impl selects the per-block kernel ("auto":
    flash on TPU, einsum elsewhere)."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    spec = P(batch_axes, axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ring_attn_local, axis_name=axis, causal=causal,
                          scale=scale, block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all head parallelism)
# ---------------------------------------------------------------------------


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float,
                   block_impl: str = "auto"):
    """Per-device body: (B, L_local, H, D) → all_to_all → full-seq
    attention on H/axis_size heads → all_to_all back.

    GQA: when the KV head count divides the axis size, the SMALL k/v
    arrays ride the all_to_all and heads are replicated after (ICI moves
    KV-sized bytes, not H-sized); otherwise KV is replicated up front.

    The per-device attention is the Pallas flash kernel on TPU (O(L)
    memory, MXU-rate blocks; GQA handled by the kernel's head grouping)
    and the plain blockwise einsum elsewhere — `block_impl` forces one
    ("flash" | "einsum") for tests."""
    from dlrover_tpu.ops.flash_attention import flash_attention

    use_flash = _use_flash_blocks(block_impl)
    axis_size = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # (B, L_local, H, D) → (B, L_full, H_local, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if k.shape[2] % axis_size:
        groups = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    q_full = seq_to_heads(q)
    k_full = seq_to_heads(k)
    v_full = seq_to_heads(v)
    if use_flash:
        # (B, L, H, D) → kernel layout (B, H, L, D); GQA head grouping
        # happens inside the kernel's index maps — local q head j maps
        # to local kv head j // rep, matching the einsum path's repeat
        qt, kt, vt = (t.transpose(0, 2, 1, 3)
                      for t in (q_full, k_full, v_full))
        out = flash_attention(qt, kt, vt, causal, sm_scale=scale)
        return heads_to_seq(out.transpose(0, 2, 1, 3))
    rep = q_full.shape[2] // k_full.shape[2]
    if rep > 1:
        # local q heads j map to local kv head j // rep — the same
        # assignment as a global pre-split repeat, since contiguous head
        # blocks land on each device
        k_full = jnp.repeat(k_full, rep, axis=2)
        v_full = jnp.repeat(v_full, rep, axis=2)
    l_full = q_full.shape[1]
    mask = None
    if causal:
        pos = jnp.arange(l_full)
        mask = jnp.where(pos[None, :] > pos[:, None], _NEG_INF,
                         0.0).astype(jnp.float32)
    o, m, l = _block_attn(q_full.astype(jnp.float32), k_full, v_full,
                          scale, mask)
    out = o / jnp.maximum(l[..., None].transpose(0, 2, 1, 3), 1e-20)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = MeshAxis.SEQUENCE,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    batch_axes=(MeshAxis.DATA, MeshAxis.FSDP),
    head_axis: Optional[str] = None,
    block_impl: str = "auto",
) -> jax.Array:
    """All-to-all sequence parallelism. q (B, S, H, D), k/v may carry
    fewer (GQA) heads. Lower latency than the ring for moderate sequence
    lengths: 2 all-to-alls instead of axis_size permutes. With
    `head_axis` (tensor parallelism) the per-device head group is divided
    again by the sequence axis, composing SP × TP in one shard_map.
    block_impl: per-device attention kernel — "auto" (flash on TPU,
    einsum elsewhere) | "flash" | "einsum"."""
    heads = q.shape[2]
    axis_size = mesh.shape[axis]
    tensor_size = mesh.shape[head_axis] if head_axis else 1
    if heads % (axis_size * tensor_size):
        raise ValueError(
            f"{heads} heads not divisible by sequence axis {axis_size}"
            + (f" × tensor axis {tensor_size}" if tensor_size > 1 else ""))
    if head_axis and k.shape[2] % tensor_size:
        raise ValueError(
            f"{k.shape[2]} kv heads not divisible by tensor axis "
            f"{tensor_size}")
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    spec = P(batch_axes, axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          scale=scale, block_impl=block_impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
