"""Pipeline parallelism: stage-sharded SPMD pipelining over the `pipe` axis.

Capability parity: atorch's PiPPy path (modules/distributed_modules/
compilers/pipe_compiler/distributed_pippy_compiler.py:378 — fx-trace,
split into stages, RPC driver, GPipe/interleaved schedules) and the
DeepSpeed 3D alternative (opt_lib/ds_3d_parallel_optimization.py:53).

TPU re-design: there is no RPC; all stages run the SAME jitted SPMD
program under a shard_map that is MANUAL only over the `pipe` axis
(jax.shard_map `axis_names`): every other mesh axis (data/fsdp/tensor/…)
stays "auto", so stage-internal parameters keep their fsdp/tensor
shardings and XLA inserts the intra-stage collectives — PP composes with
FSDP/TP the way the reference's 3D path does (ds_3d_parallel topology).

Microbatch streaming is O(M/S) per stage, not O(M): the stream is stored
round-robin across stages (microbatch m lives on stage m % S) and moves
through two single-microbatch ring buffers — an input ring rotating toward
stage 0 (each stage injects its next stored microbatch every S steps) and
an output ring rotating away from the last stage (each stage deposits the
microbatches it owns as they pass by). Per-step bandwidth is three
microbatch-sized ppermutes (activation, input ring, output ring),
independent of M. The GPipe schedule runs M + 2(S-1) steps: M + S - 1 for
the pipeline itself plus up to S - 1 more for the output ring to deliver
the last microbatch to its owner.

Autodiff through scan+ppermute yields the backward pipeline;
`jax.checkpoint` on the stage fn gives per-stage remat.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.common.jax_compat import get_vma, shard_map


def _pipeline_local(stage_params, in_store, *, stage_fn, axis_name: str,
                    num_stages: int, stored_micro: int):
    """Per-device body (manual over the pipe axis only).

    stage_params: this stage's params (leading pipe dim of size 1 already
    squeezed). in_store: (1, stored_micro, micro, ...) — this stage's
    round-robin share of the stream; in_store[0, j] is microbatch
    j * S + stage.
    """
    stage = lax.axis_index(axis_name)
    in_store = in_store[0]
    num_micro = stored_micro * num_stages
    # Since the stream is padded to a multiple of S, the final microbatch's
    # owner is stage S-1 (deposit at t = M+S-2) and the latest deposit
    # overall is u = M-2 at owner S-2 (t = M+2S-4), so M + 2S - 3 steps
    # suffice; S == 1 degenerates to plain sequential execution.
    steps = num_micro + max(2 * num_stages - 3, 0)

    micro_shape = in_store.shape[1:]
    # carries hold per-stage values: mark them varying over the pipe axis
    # so the vma check accepts the ppermute outputs fed back into the scan
    zeros = _varying(jnp.zeros(micro_shape, in_store.dtype), axis_name)
    out_store0 = jnp.zeros_like(in_store)  # varying: derived from in_store

    fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    bwd_perm = [(i, (i - 1) % num_stages) for i in range(num_stages)]

    def step(carry, t):
        act, in_slot, out_slot, out_store = carry

        # -- input ring: every S steps each stage loads its next stored
        # microbatch into the slot currently at its position; the slot
        # reaches stage 0 exactly when that microbatch is due.
        load_idx = jnp.minimum(t // num_stages, stored_micro - 1)
        in_slot = jnp.where(t % num_stages == 0,
                            in_store[load_idx], in_slot)

        # -- stage 0 ingests microbatch t (garbage after the stream ends;
        # those outputs are never deposited)
        x = jnp.where(stage == 0, in_slot, act)
        y = stage_fn(stage_params, x)

        # -- output ring: the last stage writes its fresh output into the
        # slot at its position, then whichever stage owns the slot's
        # content deposits it. Content u at stage s (after the write):
        #   s == S-1: u = t - (S-1)
        #   else:     u = t - (S-1) - (s+1)
        produced = t - (num_stages - 1)
        out_slot = jnp.where(
            jnp.logical_and(stage == num_stages - 1,
                            jnp.logical_and(produced >= 0,
                                            produced < num_micro)),
            y, out_slot)
        u = jnp.where(stage == num_stages - 1,
                      t - (num_stages - 1),
                      t - num_stages - stage)
        deposit = jnp.logical_and(
            jnp.logical_and(u >= 0, u < num_micro),
            u % num_stages == stage)
        dep_idx = jnp.clip(u // num_stages, 0, stored_micro - 1)
        current = lax.dynamic_index_in_dim(out_store, dep_idx, 0,
                                           keepdims=False)
        out_store = lax.dynamic_update_index_in_dim(
            out_store, jnp.where(deposit, out_slot, current), dep_idx, 0)

        # -- rotate: activations toward higher stages, input ring toward
        # stage 0, output ring away from the last stage
        act = lax.ppermute(y, axis_name, fwd_perm)
        in_slot = lax.ppermute(in_slot, axis_name, bwd_perm)
        out_slot = lax.ppermute(out_slot, axis_name, fwd_perm)
        return (act, in_slot, out_slot, out_store), None

    (_, _, _, out_store), _ = lax.scan(
        step, (zeros, zeros, zeros, out_store0), jnp.arange(steps))
    return out_store[None]


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    inputs: jax.Array,
    axis: str = MeshAxis.PIPE,
    remat: bool = False,
) -> jax.Array:
    """Run `inputs` (num_microbatches, micro, ...) through the pipeline.

    stacked_params: pytree whose leaves have a leading stage dim of size
    mesh.shape[axis]; stage_fn(params_one_stage, x) -> y with y.shape ==
    x.shape (uniform-stage contract, same as GPipe splits). Leaves may be
    sharded over other mesh axes (fsdp/tensor) on their trailing dims —
    those axes are auto inside the pipe shard_map, so XLA keeps the
    sharding and inserts the intra-stage collectives. The micro (row) dim
    sharding likewise flows through the auto axes — each data replica
    pipelines its own row shard.
    """
    num_stages = mesh.shape[axis]
    num_micro = inputs.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # round-robin storage layout: padded[j * S + s] -> stage s, slot j
    pad = (-num_micro) % num_stages
    if pad:
        inputs = jnp.concatenate(
            [inputs, jnp.zeros((pad,) + inputs.shape[1:], inputs.dtype)])
    stored = inputs.shape[0] // num_stages
    staged = inputs.reshape((stored, num_stages) + inputs.shape[1:])
    staged = jnp.swapaxes(staged, 0, 1)  # (S, stored, micro, ...)

    def body(params, x):
        squeezed = jax.tree.map(lambda p: p[0], params)
        return _pipeline_local(
            squeezed, x, stage_fn=fn, axis_name=axis,
            num_stages=num_stages, stored_micro=stored)

    params_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    piped = shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, P(axis)),
        out_specs=P(axis),
        axis_names=frozenset({axis}),
    )
    out = piped(stacked_params, staged)   # (S, stored, micro, ...)
    out = jnp.swapaxes(out, 0, 1).reshape(
        (stored * num_stages,) + out.shape[2:])
    return out[:num_micro]


def _varying(x, axis_name):
    """Mark x as varying over the pipe axis (idempotent). On runtimes
    without vma tracking (no lax.pcast) there is nothing to mark."""
    if axis_name in get_vma(x):
        return x
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")


def pipeline_train(
    mesh: Mesh,
    chunk_fn: Callable[[Any, jax.Array], jax.Array],
    chunk_params: Any,
    shared_params: Any,
    enter_fn: Callable[[Any, jax.Array], jax.Array],
    exit_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    tokens: jax.Array,
    targets: jax.Array,
    num_rounds: int = 1,
    axis: str = MeshAxis.PIPE,
    remat: bool = False,
    chunk_has_aux: bool = False,
    activation_groups: int = 0,
) -> jax.Array:
    """Circular (interleaved) pipeline producing the mean microbatch loss.

    chunk_has_aux: chunk_fn returns (act, aux_scalar) — per-chunk
    auxiliary losses (MoE router load-balancing) accumulated over every
    VALID (chunk, microbatch) pair and folded into the returned loss as
    their microbatch mean, matching the dense trainer's
    `ce + moe_aux_loss` objective (models/llama_moe.py
    moe_cross_entropy_loss; each chunk sees each microbatch exactly
    once, so the sum over valid steps is the sum over layers).

    The schedule generalizes GPipe the way Megatron's interleaved 1F1B
    generalizes plain 1F1B (reference: PiPPy schedules consumed at
    distributed_pippy_compiler.py:378): layers split into S×num_rounds
    chunks, chunk g living on stage g % S, so each activation loops the
    ring num_rounds times. Steps = ceil(M/S)·S·C + S − 1 with only the
    S − 1 fill/drain steps idle per chunk — the bubble shrinks by the
    round count C vs GPipe. C = 1 is the plain schedule (M + S − 1 steps).

    TPU-first design decisions vs the round-2 ring-buffer version:
    - The model ENTERS the pipeline at stage 0 (enter_fn: embedding) and
      EXITS at the last stage (exit_fn: norm + head + per-row loss),
      selected by `jnp.where` on the stage index. SPMD uniformity note:
      `lax.cond` on a stage-varying predicate deadlocks — devices taking
      different branches reach the auto-axis collectives in divergent
      orders against the step's global ppermute (observed on the CPU
      backend) — so every device computes both sides and selects. The
      waste is the enter/exit bodies once per step per device: keep
      enter_fn cheap (gather embedding, not the one-hot matmul); the
      exit head matmul costs V/(V + 12·H·layers_per_chunk) of a step's
      FLOPs (~7.5% for Llama-7B at 8 layers/chunk) — the price of
      O(1) per-step comm and no output ring. For C > 1 a lax.cond on a
      stage-INDEPENDENT predicate (which steps can need enter/exit is a
      function of t alone, so every device branches identically — no
      deadlock) executes those bodies on only ~1/C of steps; measured
      full-vs-stubbed-exit wall deltas on the 8-device CPU mesh drop
      from 7-28% at C=1 to noise at C=2
      (tools/measure_pipeline_overhead.py). Uniform execution also
      means shared params may keep fsdp/tensor shardings: their
      collectives run on every device in the same order.
    - exit_fn returns UNREDUCED per-row losses (micro,), accumulated in
      the carry; only the (micro,) loss rows leave the last stage, so
      there is no output ring and no logits materialization; per-step
      comm is ONE activation ppermute. The cross-device reductions (psum
      over pipe, row mean) happen after the scan.
    - tokens/targets (M, micro, seq) ride in replicated over pipe — raw
      int32 microbatches are tiny next to hidden activations, which is
      what made the round-2 input ring necessary (it carried embedded
      activations).

    chunk_params: leaves (C, S, layers_per_chunk, ...) — chunk r·S + s is
    [r, s]; trailing dims may be auto-sharded (fsdp/tensor), composing
    PP × TP × FSDP × DP in one partial-auto shard_map. shared_params
    (embedding/norm/head) replicate over pipe, auto elsewhere.
    enter_fn(shared, tok_micro) -> (micro, seq, H) activation;
    chunk_fn(params[r·S+s], act) -> act;
    exit_fn(shared, act, tgt_micro) -> (micro,) per-row losses, no
    cross-row reduction.
    Returns the scalar mean loss over all microbatch rows.
    """
    num_stages = mesh.shape[axis]
    num_micro = tokens.shape[0]
    num_groups = -(-num_micro // num_stages)     # ceil
    steps = num_groups * num_stages * num_rounds + num_stages - 1
    fn = jax.checkpoint(chunk_fn) if remat else chunk_fn
    # act shape from the REAL dtypes (before any fp32 boundary cast)
    act_shape = jax.eval_shape(enter_fn, shared_params, tokens[0])

    # XLA-CPU workaround: shard_map's transpose psums the SHARED params'
    # gradients over pipe (they enter replicated), and the CPU backend
    # CHECK-fails promoting that half-precision all-reduce ("Invalid
    # binary instruction opcode copy"). Route shared params through an
    # fp32 boundary — the transpose psum then runs fp32 — and cast back
    # to the compute dtype inside, so ALL compute (and the activation
    # ppermute, which the CPU backend handles fine in bf16) keeps the
    # real dtypes. TPU/GPU take the direct path.
    _half = (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))
    cast_boundary = (jax.default_backend() == "cpu" and any(
        jnp.dtype(leaf.dtype) in _half
        for leaf in jax.tree.leaves(shared_params)
        if hasattr(leaf, "dtype")))
    if cast_boundary:
        shared_dtypes = jax.tree.map(lambda l: l.dtype, shared_params)
        shared_params = jax.tree.map(
            lambda l: l.astype(jnp.float32)
            if jnp.dtype(l.dtype) in _half else l, shared_params)

        def _restore_shared(shared):
            # order matters: mark the fp32 leaves VARYING first, THEN
            # cast to the compute dtype. The grad psum is inserted at
            # the pvary transpose — done this way it reduces the fp32
            # cotangent; cast-first would put the bf16 all-reduce right
            # back (psum_invariant on the bf16 value, the instruction
            # the CPU compiler CHECK-fails on)
            shared = jax.tree.map(lambda l: _varying(l, axis), shared)
            return jax.tree.map(lambda l, d: l.astype(d), shared,
                                shared_dtypes)
    else:
        def _restore_shared(shared):
            return shared

    micro = tokens.shape[1]
    fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def body(chunk_params, shared, tokens, targets):
        shared = _restore_shared(shared)
        # chunk leaves arrive (C, 1, layers_per_chunk, ...): drop the
        # sharded stage dim
        local_chunks = jax.tree.map(lambda p: p[:, 0], chunk_params)
        stage = lax.axis_index(axis)
        S, C, M = num_stages, num_rounds, num_micro

        def step(carry, t):
            act, loss_rows, aux_acc = carry
            ts = t - stage
            # the activation arriving here was injected at stage 0 at
            # step ts − r·S; see the schedule proof in the docstring
            r = jnp.clip((ts // S) % C, 0, C - 1)
            m = (ts // (S * C)) * S + ts % S
            valid = jnp.logical_and(ts >= 0, m < M)
            m_safe = jnp.clip(m, 0, M - 1)

            def fresh(_):
                tok = lax.dynamic_index_in_dim(tokens, m_safe, 0,
                                               keepdims=False)
                return _varying(enter_fn(shared, tok).astype(act.dtype),
                                axis)

            # SPMD uniformity allows lax.cond only on stage-INDEPENDENT
            # predicates (every device must take the same branch — see
            # the docstring's deadlock note). Enter is needed only when
            # stage 0's round index (t // S) % C is 0, and that is a
            # function of t alone — so for C > 1 the cond skips the
            # enter body entirely on C−1 of C step-groups, on every
            # device, instead of computing-and-discarding it each step.
            enter_round = ((t // S) % C == 0) if C > 1 else True

            def enter_true(act):
                return jnp.where(jnp.logical_and(stage == 0, r == 0),
                                 fresh(None), act)

            if C > 1:
                x = lax.cond(enter_round, enter_true, lambda a: a, act)
            else:
                x = enter_true(act)
            params_r = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, r, 0,
                                                   keepdims=False),
                local_chunks)
            if chunk_has_aux:
                y, aux = fn(params_r, x)
                aux_acc = aux_acc + jnp.where(
                    valid, aux.astype(jnp.float32), 0.0)
            else:
                y = fn(params_r, x)

            def take_loss(_):
                tgt = lax.dynamic_index_in_dim(targets, m_safe, 0,
                                               keepdims=False)
                return _varying(
                    exit_fn(shared, y, tgt).astype(jnp.float32), axis)

            do_loss = jnp.logical_and(
                jnp.logical_and(stage == S - 1, r == C - 1), valid)

            def exit_true(loss_rows):
                return loss_rows + jnp.where(do_loss, take_loss(None),
                                             0.0)

            # Same uniform-cond trick for the exit: the last stage holds
            # a final-round activation only at steps with
            # ((t−S+1) // S) % C == C−1 — again a function of t alone.
            # For C > 1 this cuts the exit body (norm + head matmul +
            # loss — the waste the docstring prices at
            # V/(V + 12·H·layers_per_chunk) of a step) to 1/C of the
            # steps.
            if C > 1:
                exit_round = jnp.logical_and(
                    t >= S - 1, ((t - (S - 1)) // S) % C == C - 1)
                loss_rows = lax.cond(exit_round, exit_true,
                                     lambda lr: lr, loss_rows)
            else:
                loss_rows = exit_true(loss_rows)
            act = lax.ppermute(y, axis, fwd_perm)
            return (act, loss_rows, aux_acc), None

        act0 = _varying(jnp.zeros(act_shape.shape, act_shape.dtype), axis)
        loss0 = _varying(jnp.zeros((micro,), jnp.float32), axis)
        aux0 = _varying(jnp.zeros((), jnp.float32), axis)
        carry0 = (act0, loss0, aux0)
        if activation_groups and steps > activation_groups:
            # 1F1B-style memory profile WITHOUT changing the schedule
            # (reference analog: PiPPy's 1F1B bounds live microbatch
            # activations to ~num_stages,
            # distributed_pippy_compiler.py:378). The step scan's
            # linearization residuals grow O(steps) ~ O(M); grouping
            # the scan into checkpointed windows of `activation_groups`
            # (= num_stages) steps stores only the carry at group
            # boundaries and recomputes one group at a time in the
            # backward — live residuals bound to one group (~S
            # microbatches in flight), bubble unchanged, at the
            # standard one-extra-forward remat cost.
            pad_steps = (-steps) % activation_groups
            ts = jnp.arange(steps + pad_steps)  # padded tail: valid=False
            groups = ts.reshape(-1, activation_groups)

            @jax.checkpoint
            def group_body(carry, ts_g):
                return lax.scan(step, carry, ts_g)

            (_, loss_rows, aux_acc), _ = lax.scan(group_body, carry0,
                                                  groups)
        else:
            (_, loss_rows, aux_acc), _ = lax.scan(step, carry0,
                                                  jnp.arange(steps))
        # only the last stage accumulated anything; reductions (pipe
        # psum here, row mean outside) stay OUT of the cond branches
        return lax.psum(loss_rows, axis), lax.psum(aux_acc, axis)

    params_spec = jax.tree.map(lambda _: P(None, axis), chunk_params)
    rep = jax.tree.map(lambda _: P(), shared_params)
    piped = shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, rep, P(), P()),
        out_specs=(P(), P()),
        axis_names=frozenset({axis}),
    )
    loss_rows, aux_total = piped(chunk_params, shared_params, tokens,
                                 targets)
    # mean over all M·micro rows; the cross-replica reduce of the row
    # mean happens here, outside the pipeline scan. Aux losses: each
    # (chunk, microbatch) contributed once → microbatch mean matches the
    # dense objective's per-batch aux sum.
    loss = jnp.mean(loss_rows) / num_micro
    if chunk_has_aux:
        loss = loss + aux_total / num_micro
    return loss


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)


def sequential_oracle(stage_fn, per_stage_params, inputs) -> jax.Array:
    """Reference semantics: every microbatch through every stage in
    order (what the pipeline must equal)."""
    outs = []
    for i in range(inputs.shape[0]):
        x = inputs[i]
        for params in per_stage_params:
            x = stage_fn(params, x)
        outs.append(x)
    return jnp.stack(outs)
