"""Pipeline parallelism: stage-sharded SPMD pipelining over the `pipe` axis.

Capability parity: atorch's PiPPy path (modules/distributed_modules/
compilers/pipe_compiler/distributed_pippy_compiler.py:378 — fx-trace,
split into stages, RPC driver, GPipe/interleaved schedules) and the
DeepSpeed 3D alternative (opt_lib/ds_3d_parallel_optimization.py:53).

TPU re-design: there is no RPC; all stages run the SAME jitted SPMD
program. Stage parameters are stacked on a leading dim sharded over the
`pipe` mesh axis; microbatches stream through a `lax.scan` whose carry is
the activation in flight, rotated stage-to-stage with `ppermute` each
step (GPipe schedule: num_micro + num_stages - 1 steps, bubble fraction
(S-1)/(M+S-1)). Autodiff through scan+ppermute yields the backward
pipeline; `jax.checkpoint` on the stage fn gives per-stage remat.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from dlrover_tpu.common.constants import MeshAxis


def _pipeline_local(stage_params, inputs, *, stage_fn, axis_name: str,
                    num_microbatches: int):
    """Per-device body. stage_params: this stage's params (leading stage
    dim of size 1 already squeezed by shard_map). inputs: (M, micro, ...)
    full microbatch stream (replicated across pipe)."""
    stage = lax.axis_index(axis_name)
    num_stages = lax.psum(1, axis_name)
    steps = num_microbatches + num_stages - 1  # static: mesh-sized

    micro_shape = inputs.shape[1:]
    outputs0 = jnp.zeros((num_microbatches,) + micro_shape,
                         dtype=inputs.dtype)
    state0 = jnp.zeros(micro_shape, inputs.dtype)

    def step(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (garbage after the stream ends —
        # masked out at collection time)
        inp = inputs[jnp.minimum(t, num_microbatches - 1)]
        state = jnp.where(stage == 0, inp, state)
        state = stage_fn(stage_params, state)
        # last stage emits microbatch t - (S-1) once warmed up
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(stage == num_stages - 1, out_idx >= 0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid, state,
                      lax.dynamic_index_in_dim(
                          outputs, jnp.maximum(out_idx, 0), 0,
                          keepdims=False)),
            jnp.maximum(out_idx, 0), 0)
        state = lax.ppermute(
            state, axis_name,
            [(i, (i + 1) % num_stages) for i in range(num_stages)])
        return (state, outputs), None

    (_, outputs), _ = lax.scan(step, (state0, outputs0),
                               jnp.arange(steps))
    # outputs are only populated on the last stage; psum broadcasts them
    # (every other stage holds zeros)
    mask = (stage == num_stages - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    inputs: jax.Array,
    axis: str = MeshAxis.PIPE,
    remat: bool = False,
    batch_axes=None,
) -> jax.Array:
    """Run `inputs` (num_microbatches, micro, ...) through the pipeline.

    stacked_params: pytree whose leaves have a leading stage dim of size
    mesh.shape[axis]; stage_fn(params_one_stage, x) -> y with y.shape ==
    x.shape (uniform-stage contract, same as GPipe splits).

    batch_axes: mesh axes the micro (row) dim is sharded over — PP×DP
    composition: each data replica pipelines only its row shard. None =
    replicated rows (pure PP).
    """
    num_stages = mesh.shape[axis]
    num_microbatches = inputs.shape[0]
    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    def body(params, x):
        squeezed = jax.tree.map(lambda p: p[0], params)
        return _pipeline_local(
            squeezed, x, stage_fn=fn, axis_name=axis,
            num_microbatches=num_microbatches)

    params_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    data_spec = P(None, batch_axes) if batch_axes is not None else P()
    piped = shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, data_spec),
        out_specs=data_spec,
        check_vma=False,
    )
    return piped(stacked_params, inputs)


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)


def sequential_oracle(stage_fn, per_stage_params, inputs) -> jax.Array:
    """Reference semantics: every microbatch through every stage in
    order (what the pipeline must equal)."""
    outs = []
    for i in range(inputs.shape[0]):
        x = inputs[i]
        for params in per_stage_params:
            x = stage_fn(params, x)
        outs.append(x)
    return jnp.stack(outs)
