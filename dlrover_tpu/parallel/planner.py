"""Online parallelism re-planning: pick the best mesh for ANY world size.

Why: before this module a world-size change re-formed the *same*
data-parallel shape — ``choose_accumulation`` raises when the global
batch does not divide by the new dp size, so only divisor-friendly
worlds worked and an awkward resize silently wasted chips or forced a
full checkpoint round-trip. DynaTrain (fast online parallelism
switching) and ElasWave (elastic-native hybrid-parallel training) in
PAPERS.md name the alternative this module implements: at the
membership cut, enumerate every feasible DP×TP×PP(×DCN) factorization
of the surviving chip count, score each against the model's memory
footprint, a predicted step time derived from the MFU model
(obs/mfu.py), and the bytes a live migration from the previous plan
would move — then emit ONE deterministic plan, keyed by the rendezvous
generation token, that master and every worker agree on without
negotiation.

Deliberately stdlib-only: the master (no jax) computes plans in the
rendezvous path (master/rendezvous.py ``compute_shard_plan``) and the
worker applies them when building its mesh
(trainer/elastic_loop.py). Determinism is the correctness property —
the plan is a pure function of (world, profile, previous plan,
generation), so every rank that asks gets the same answer and the
resize completes in one rendezvous round.

The batch contract: a dp size that does not divide the requested
global batch rounds the batch DOWN to the nearest dp multiple — a
*deliberate*, recorded adjustment (``batch_adjusted`` + both values in
the plan; the worker trims its input batches and records a flight
event), never a silent wrong batch and never a crash. Candidates whose
dp exceeds the requested batch are infeasible (rounding up would
invent data).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

# -- scoring model coefficients (documented, deterministic) -----------------
# Baseline fraction of peak a well-shaped single-axis data-parallel run
# achieves (BENCH_r05: 0.59-0.70 measured); the per-axis penalties below
# discount it. These are a coarse analytic prior, not a measurement —
# their job is to RANK candidates consistently, and the ranking is what
# determinism and the tests pin down.
_BASE_EFFICIENCY = 0.6
# tensor-parallel collectives ride every layer's critical path
_TENSOR_PENALTY = 0.05
# fsdp allgather/reduce-scatter overlaps well; mild discount
_FSDP_PENALTY = 0.01
# cross-slice (DCN) reduce per step
_DCN_PENALTY = 0.03
# assumed migration bandwidth for the migration-cost term (host RAM /
# ICI class transfers measured by bench_restore; the exact figure only
# scales the migration term relative to the step-time horizon)
_MIGRATION_BYTES_PER_S = 2e9
# steps the plan is amortized over when trading step time vs migration
_HORIZON_STEPS = 200.0
# relative penalty weight for shrinking the requested global batch
# (full weight: a shrunken batch changes training semantics — prefer a
# slightly slower mesh that preserves the batch over one that trims it)
_BATCH_PENALTY = 1.0
# HBM headroom reserved for activations/workspace when a memory budget
# is known
_HBM_HEADROOM = 0.85


@dataclasses.dataclass(frozen=True)
class ModelProfile:
    """What the planner needs to know about the model + hardware.

    Fed master-side from ModelInfo reports (flops/bytes) and chip-stats
    HBM totals; every field has a safe zero default so a plan can be
    computed before the first worker ever reported (scores then ignore
    the unknown terms instead of guessing)."""

    param_count: int = 0
    param_bytes: int = 0
    flops_per_token: float = 0.0
    peak_flops_per_chip: float = 0.0
    seq_len: int = 0
    global_batch: int = 0
    # optimizer state bytes per param byte (adam: two f32 moments over
    # (possibly) bf16 params ~ 2-4x; 2.0 is the exact-dtype adam figure)
    optimizer_bytes_per_param_byte: float = 2.0
    # per-chip HBM budget in bytes; 0 = unconstrained (CPU harnesses)
    hbm_bytes_per_chip: int = 0
    max_micro_per_replica: int = 8
    # model-dim divisibility granules (ModelInfo): a tensor axis must
    # divide tensor_divisor (gcd of heads/kv/mlp/vocab dims), an fsdp
    # axis fsdp_divisor (the embed dim). 0 = unknown — no filtering
    # (the worker's trace probe + loud fallback catches the rest).
    tensor_divisor: int = 0
    fsdp_divisor: int = 0

    def state_bytes(self) -> float:
        return float(self.param_bytes) * (
            1.0 + max(0.0, self.optimizer_bytes_per_param_byte))


@dataclasses.dataclass(frozen=True)
class MeshCandidate:
    """One DP×TP×PP(×DCN) factorization of the world's chips. The
    ``data``/``fsdp`` split both carry the batch dim (parallel/mesh.py
    ``data_axes``); fsdp additionally shards the state."""

    dcn: int = 1
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1

    @property
    def total(self) -> int:
        return self.dcn * self.data * self.fsdp * self.tensor * self.pipe

    @property
    def dp(self) -> int:
        """Replicas the batch shards over (dcn + data + fsdp jointly)."""
        return self.dcn * self.data * self.fsdp

    def state_shards(self) -> int:
        """How many ways the param/optimizer state is sharded (dp
        replicas replicate; fsdp/tensor/pipe shard)."""
        return self.fsdp * self.tensor * self.pipe

    def as_dict(self) -> Dict[str, int]:
        return {"dcn": self.dcn, "data": self.data, "fsdp": self.fsdp,
                "tensor": self.tensor, "pipe": self.pipe}


def _divisors(n: int) -> List[int]:
    out = [d for d in range(1, n + 1) if n % d == 0]
    return out


def enumerate_meshes(chips: int, slices: int = 1,
                     max_tensor: int = 8, max_pipe: int = 8
                     ) -> List[MeshCandidate]:
    """Every feasible factorization of ``chips`` into
    dcn×data×fsdp×tensor×pipe, deterministic order.

    ``slices`` > 1 pins the dcn axis to the slice count when it divides
    the chips (PR 8's hierarchical contract: the dcn axis exists
    precisely to carry the cross-fabric split); a chip count the slices
    do not divide falls back to dcn=1 — the caller decides whether that
    world is acceptable. Tensor/pipe caps keep the latency-bound axes
    inside one ICI domain."""
    chips = max(1, int(chips))
    dcn = slices if slices > 1 and chips % slices == 0 else 1
    per_slice = chips // dcn
    candidates: List[MeshCandidate] = []
    for tensor in _divisors(per_slice):
        if tensor > max_tensor:
            continue
        rest_t = per_slice // tensor
        for pipe in _divisors(rest_t):
            if pipe > max_pipe:
                continue
            pool = rest_t // pipe
            for fsdp in _divisors(pool):
                candidates.append(MeshCandidate(
                    dcn=dcn, data=pool // fsdp, fsdp=fsdp,
                    tensor=tensor, pipe=pipe))
    return candidates


def adjust_global_batch(requested: int, dp: int) -> Tuple[int, bool]:
    """The deliberate batch adjustment: round DOWN to the nearest dp
    multiple (never up — rounding up would invent data the input
    pipeline does not have). Returns (batch, adjusted). A dp larger
    than the requested batch returns (0, True): infeasible."""
    requested = int(requested)
    if requested <= 0:
        return max(dp, 0), False
    if dp <= 0 or dp > requested:
        return 0, True
    adjusted = (requested // dp) * dp
    return adjusted, adjusted != requested


def choose_accum(global_batch: int, dp: int,
                 max_micro_per_replica: int) -> Tuple[int, int]:
    """(accum_steps, micro_batch_global) for a dp-divisible batch —
    the same policy as trainer.train_step.choose_accumulation,
    restated here so the jax-free master can plan with it."""
    per_replica = global_batch // dp
    accum = 1
    while (per_replica % accum
           or per_replica // accum > max(1, max_micro_per_replica)):
        accum += 1
        if accum > per_replica:
            accum = per_replica
            break
    return accum, global_batch // accum


def _efficiency(candidate: MeshCandidate, accum: int,
                axis_discounts: Optional[Dict[str, float]] = None
                ) -> float:
    """Predicted fraction of aggregate peak the candidate sustains.
    The pipeline term is the classic bubble fraction with ``accum``
    microbatches: m / (m + p - 1).

    ``axis_discounts`` are LEARNED multiplicative corrections from the
    calibration loop (parallel/calibration.py: measured step time vs
    this very prediction, per axis, normalized against shapes not
    using the axis): a discount < 1 on an axis the fleet measured
    slower than the prior predicts shifts scoring away from it. Only
    active axes (> 1 way) are discounted, so plain data parallelism
    stays the un-discounted baseline the corrections are relative to."""
    eff = _BASE_EFFICIENCY
    eff *= 1.0 / (1.0 + _TENSOR_PENALTY * (candidate.tensor - 1))
    eff *= 1.0 / (1.0 + _FSDP_PENALTY * (candidate.fsdp - 1))
    eff *= 1.0 / (1.0 + _DCN_PENALTY * (candidate.dcn - 1))
    if candidate.pipe > 1:
        eff *= accum / (accum + candidate.pipe - 1.0)
    if axis_discounts:
        for axis, ways in (("dcn", candidate.dcn),
                           ("data", candidate.data),
                           ("fsdp", candidate.fsdp),
                           ("tensor", candidate.tensor),
                           ("pipe", candidate.pipe)):
            discount = axis_discounts.get(axis)
            if ways > 1 and discount and discount > 0:
                eff *= float(discount)
    return eff


def migration_bytes(candidate: MeshCandidate,
                    prev_mesh: Optional[Dict[str, int]],
                    profile: ModelProfile,
                    prev_world: int = 0, world: int = 0) -> float:
    """Bytes a live migration from ``prev_mesh`` moves. A changed
    state sharding (fsdp/tensor/pipe) re-shards every replica's state;
    a pure dp resize only fills the ranks with no local replica (the
    peer-restore path serves survivors from their own cache)."""
    if prev_mesh is None:
        return 0.0
    state = profile.state_bytes()
    prev = MeshCandidate(**{k: int(prev_mesh.get(k, 1))
                            for k in ("dcn", "data", "fsdp", "tensor",
                                      "pipe")})
    if (prev.fsdp, prev.tensor, prev.pipe) != (
            candidate.fsdp, candidate.tensor, candidate.pipe):
        # every chip's shard layout changes: the whole state moves once
        return state
    if prev_world and world and world > prev_world:
        # grow: only the new replicas' copies transfer
        return state * (world - prev_world) / max(1, prev_world)
    # shrink or same size with unchanged sharding: survivors keep their
    # shards; only evicted replicas' data (already replicated) vanishes
    return 0.0


def score_candidate(candidate: MeshCandidate, profile: ModelProfile,
                    prev_mesh: Optional[Dict[str, int]] = None,
                    prev_world: int = 0,
                    axis_discounts: Optional[Dict[str, float]] = None
                    ) -> Optional[Dict[str, Any]]:
    """Score one candidate; None when it is infeasible (batch smaller
    than dp, or the state cannot fit the HBM budget)."""
    requested = profile.global_batch
    batch, adjusted = adjust_global_batch(requested, candidate.dp)
    if batch <= 0:
        return None
    # model-dim divisibility: a tensor/fsdp way that does not divide
    # the dims it would shard cannot trace — infeasible by construction
    if (candidate.tensor > 1 and profile.tensor_divisor > 0
            and profile.tensor_divisor % candidate.tensor):
        return None
    if (candidate.fsdp > 1 and profile.fsdp_divisor > 0
            and profile.fsdp_divisor % candidate.fsdp):
        return None
    accum, micro = choose_accum(batch, candidate.dp,
                                profile.max_micro_per_replica)
    # memory fit: per-chip state bytes + one f32 grad accumulator over
    # the same sharding (the scan's grad_sum)
    per_chip = 0.0
    if profile.param_bytes > 0:
        shards = candidate.state_shards()
        per_chip = (profile.state_bytes()
                    + 4.0 * profile.param_count) / shards
        if (profile.hbm_bytes_per_chip > 0
                and per_chip > profile.hbm_bytes_per_chip
                * _HBM_HEADROOM):
            return None
    # predicted step time from the MFU model: tokens × FLOPs/token over
    # the discounted aggregate peak. Unknown model/peak → 0 (candidates
    # then rank purely on migration + batch terms + tie-break).
    eff = _efficiency(candidate, accum, axis_discounts)
    step_s = 0.0
    if (profile.flops_per_token > 0 and profile.peak_flops_per_chip > 0
            and profile.seq_len > 0 and batch > 0):
        tokens = batch * profile.seq_len
        step_s = (tokens * profile.flops_per_token
                  / (profile.peak_flops_per_chip * candidate.total
                     * eff))
        if adjusted and requested > 0:
            # a smaller batch trains fewer tokens per step: normalize
            # the per-token cost so shrinking the batch is not scored
            # as a free speedup
            step_s *= requested / batch
    mig = migration_bytes(candidate, prev_mesh, profile,
                          prev_world=prev_world, world=candidate.total)
    score = step_s * _HORIZON_STEPS + mig / _MIGRATION_BYTES_PER_S
    if adjusted and requested > 0:
        # scale the batch-shrink penalty to the step-time term when one
        # exists (so it competes on the same axis); with no FLOPs model
        # the penalty is the only non-zero term and ranks on its own
        scale = step_s * _HORIZON_STEPS if step_s > 0 else 1.0
        score += (_BATCH_PENALTY * (requested - batch) / requested
                  * scale)
    return {
        "mesh": candidate.as_dict(),
        "feasible": True,
        "score": score,
        "predicted_step_s": step_s,
        "predicted_efficiency": eff,
        "migration_bytes": mig,
        "state_bytes_per_chip": per_chip,
        "global_batch": batch,
        "requested_global_batch": requested,
        "batch_adjusted": bool(adjusted),
        "accum_steps": accum,
        "micro_batch": micro,
        "dp": candidate.dp,
    }


def plan_parallelism(world: Dict[int, int],
                     profile: Optional[ModelProfile] = None,
                     slices: int = 1,
                     prev_plan: Optional[Dict[str, Any]] = None,
                     generation: int = 0,
                     epoch: int = 0,
                     round_: int = 0,
                     max_tensor: int = 8,
                     max_pipe: int = 8,
                     axis_discounts: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """THE planner entry: (new world, model profile, previous plan) →
    one deterministic JSON-safe plan.

    ``world``: rank → local chip count (the rendezvous world map).
    ``slices``: formed ICI slices (dcn axis size when it divides).
    ``prev_plan``: the previously stamped plan (its mesh feeds the
    migration term so a resize that can keep the sharding is preferred
    over an equivalent-speed one that re-shards everything).
    ``axis_discounts``: learned per-axis efficiency corrections from
    the calibration loop — part of the plan's deterministic inputs
    (callers memoize on them too) and stamped into the plan so the
    flight record shows WHICH prior scored it.

    Always returns a plan: when no candidate is feasible (a memory
    budget nothing fits, or an empty world) the least-infeasible
    candidate is returned with ``feasible: false`` — callers must treat
    that loudly (the worker falls back to the checkpoint-restart path),
    but the planner never wedges the fleet by answering nothing."""
    profile = profile or ModelProfile()
    ranks = sorted(world)
    chips = sum(int(world[r]) for r in ranks)
    prev_mesh = (prev_plan or {}).get("mesh")
    prev_world = int((prev_plan or {}).get("total_devices", 0) or 0)
    base = {
        "version": 1,
        "generation": int(generation),
        "epoch": int(epoch),
        "round": int(round_),
        "world_size": len(ranks),
        "ranks": ranks,
        "total_devices": chips,
        "slices": int(slices),
    }
    if chips <= 0:
        return dict(base, feasible=False, mesh=MeshCandidate().as_dict(),
                    reason="empty world", global_batch=0,
                    requested_global_batch=profile.global_batch,
                    batch_adjusted=False, accum_steps=1, micro_batch=0)
    best: Optional[Dict[str, Any]] = None
    best_key: Optional[Tuple] = None
    # two passes: the capped enumeration first (tensor/pipe inside one
    # ICI domain), then — only when NOTHING capped is feasible (a prime
    # world larger than the batch, say) — uncapped: a tensor axis the
    # size of the world is slow but FEASIBLE, and "any world size" means
    # the planner answers with a working shape, not a shrug
    for pass_caps in ((max_tensor, max_pipe), (chips, chips)):
        for candidate in enumerate_meshes(chips, slices=slices,
                                          max_tensor=pass_caps[0],
                                          max_pipe=pass_caps[1]):
            scored = score_candidate(candidate, profile,
                                     prev_mesh=prev_mesh,
                                     prev_world=prev_world,
                                     axis_discounts=axis_discounts)
            if scored is None:
                continue
            # deterministic total order: score, then prefer the SAFE
            # axes — fewer tensor/pipe/fsdp ways (those shard model
            # dims whose divisibility the planner cannot verify; plain
            # data parallelism always applies), more data last. A
            # memory budget flips this naturally: replicated-state
            # candidates fail the fit filter, so fsdp wins when it is
            # NEEDED, not by default.
            key = (round(scored["score"], 9), candidate.tensor,
                   candidate.pipe, candidate.fsdp, -candidate.data)
            if best_key is None or key < best_key:
                best, best_key = scored, key
        if best is not None:
            break
    if best is None:
        # nothing feasible: answer the least-bad sharded-most candidate
        # LOUDLY rather than nothing — the callers' fallback path needs
        # a concrete shape to log and refuse
        fallback = max(enumerate_meshes(chips, slices=slices,
                                        max_tensor=max_tensor,
                                        max_pipe=max_pipe),
                       key=lambda c: (c.state_shards(), -c.data))
        batch, adjusted = adjust_global_batch(profile.global_batch,
                                              fallback.dp)
        return dict(base, feasible=False, mesh=fallback.as_dict(),
                    reason="no candidate fits the batch/memory budget",
                    global_batch=batch,
                    requested_global_batch=profile.global_batch,
                    batch_adjusted=bool(adjusted or batch <= 0),
                    accum_steps=1, micro_batch=batch, dp=fallback.dp)
    plan = dict(base, **best)
    plan["migration_s_estimate"] = round(
        best["migration_bytes"] / _MIGRATION_BYTES_PER_S, 3)
    if axis_discounts:
        # the calibrated prior this plan was scored with — the flight
        # record of "the loop was closed" (parallel/calibration.py)
        plan["axis_discounts"] = {k: float(v) for k, v
                                  in sorted(axis_discounts.items())}
    # did the sharding change vs the previous plan? (what the worker's
    # replan event and the goodput summary report)
    plan["resharded"] = bool(
        prev_mesh is not None and {
            k: int(prev_mesh.get(k, 1))
            for k in ("fsdp", "tensor", "pipe")} != {
            k: plan["mesh"][k] for k in ("fsdp", "tensor", "pipe")})
    return plan


def slice_mesh(plan: Dict[str, Any]) -> Dict[str, int]:
    """The per-slice portion of a plan's mesh: identical axes with
    dcn=1 — what a worker in the multi-world slice mode (host-level
    DCN sync, one jax program per slice) builds locally."""
    mesh = dict(plan.get("mesh", {}))
    mesh["dcn"] = 1
    return mesh


def plans_equivalent(a: Optional[Dict[str, Any]],
                     b: Optional[Dict[str, Any]]) -> bool:
    """Do two plans describe the same execution shape (mesh + batch +
    accumulation)? Used to detect a REAL re-plan vs a re-stamp of the
    same shape for a late joiner."""
    if not a or not b:
        return False
    keys = ("mesh", "global_batch", "accum_steps", "micro_batch",
            "total_devices")
    return all(a.get(k) == b.get(k) for k in keys)


def validate_plan(plan: Dict[str, Any], n_devices: int) -> Optional[str]:
    """Worker-side sanity check before a plan is applied; returns an
    error string (for the loud fallback event) or None when the plan
    can drive this process's mesh build."""
    if not isinstance(plan, dict) or not plan.get("mesh"):
        return "no plan"
    if not plan.get("feasible", False):
        return str(plan.get("reason") or "planner found no feasible mesh")
    mesh = plan["mesh"]
    try:
        total = math.prod(int(mesh.get(k, 1))
                          for k in ("dcn", "data", "fsdp", "tensor",
                                    "pipe"))
    except (TypeError, ValueError):
        return "malformed mesh"
    if total != int(plan.get("total_devices", -1)):
        return "mesh does not factor the planned device count"
    if n_devices > 0 and total != n_devices:
        return (f"plan covers {total} devices, this process sees "
                f"{n_devices}")
    if int(plan.get("global_batch", 0)) <= 0:
        return "non-positive planned batch"
    return None


def iter_feasible_worlds(world_sizes: Iterable[int],
                         profile: ModelProfile
                         ) -> Iterable[Tuple[int, Dict[str, Any]]]:
    """Test/diagnostic helper: plans for a sweep of world sizes (one
    chip per rank), yielding (world_size, plan)."""
    for n in world_sizes:
        yield n, plan_parallelism({r: 1 for r in range(n)}, profile)
