"""Parallelism layer: named-axis device meshes + sharding rules.

Capability parity: atorch's process-group zoo (create_parallel_group,
atorch/distributed/distributed.py:323; Megatron TP layer family,
modules/distributed_modules/layers.py) — re-designed TPU-first: one
`jax.sharding.Mesh` with named axes (data/fsdp/tensor/sequence/expert/pipe),
logical-axis rules instead of parallel module classes, and XLA-inserted
collectives over ICI/DCN.
"""

from dlrover_tpu.parallel.mesh import MeshSpec, create_mesh, use_mesh
from dlrover_tpu.parallel.sharding import (
    DEFAULT_RULES,
    make_sharding_rules,
    mesh_shardings,
)
