"""Quantized gradient all-reduce: int8/int4 codes on the wire.

Capability parity: the reference's quant_reduce CUDA kernel dequantizes
N swizzled partitions, reduces them, and requantizes the result for the
wire (atorch/atorch/ops/csrc/quantization/quant_reduce.cu:248, bound at
pt_binding.cpp:178) — the communication half of its quantization suite,
built for the slow (inter-node / DCN) gradient all-reduce. TPU
re-design: the same groupwise-symmetric scheme rides XLA collectives
inside a shard_map that is manual ONLY over the reduce axis (the
data/DCN axis — `_dcn_split` in parallel/mesh.py routes exactly this
axis across the slow fabric), so intra-slice sharding stays auto:

- ``scatter`` mode (the quant_reduce analog): each member quantizes its
  local gradient per chunk, all_to_alls the codes, dequantizes the N
  received versions of its own chunk, reduces, REquantizes, and
  all_gathers the reduced codes. Wire bytes ≈ 2x the quantized payload
  — ~4x less than a bf16 ring all-reduce, ~8x less than fp32.
- ``gather`` mode (small N): one quantization, all_gather codes+scales,
  dequantize-and-sum locally. Cheaper than scatter for N <= 4 and
  single-quantization (half the rounding error).

Accuracy: groupwise int8 keeps per-group relative error ~= 1/(2*127);
the end-to-end training-impact bound lives in
tests/test_quant_allreduce.py (loss-curve comparison vs the exact
reduce).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.ops.quantization import pack_int4, unpack_int4

DEFAULT_GROUP = 256
# below this many elements the quantization bookkeeping costs more than
# the wire savings — psum exact
MIN_QUANT_SIZE = 2048


def _quantize(x2: jax.Array, qmax: int):
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(x2 * inv), -qmax, qmax).astype(jnp.int8)
    return q, scale


def _wire_encode(q: jax.Array, bits: int) -> jax.Array:
    return pack_int4(q) if bits == 4 else q


def _wire_decode(q: jax.Array, bits: int) -> jax.Array:
    return unpack_int4(q) if bits == 4 else q


def quantized_pmean_leaf(g: jax.Array, axis_name: str, n: int,
                         bits: int = 8,
                         group_size: int = DEFAULT_GROUP,
                         mode: str = "auto") -> jax.Array:
    """Mean-reduce one gradient leaf over ``axis_name`` with quantized
    wire traffic. Must run inside a shard_map manual over ``axis_name``.
    ``bits=0`` is the exact escape hatch: a plain pmean over the manual
    axis (hierarchical meshes reduce over the dcn axis even when the
    operator wants exact arithmetic on the wire)."""
    if (bits == 0 or not jnp.issubdtype(g.dtype, jnp.floating)
            or g.size < MIN_QUANT_SIZE):
        return lax.pmean(g, axis_name)
    qmax = 127 if bits == 8 else 7
    if mode == "auto":
        mode = "gather" if n <= 4 else "scatter"

    flat = g.reshape(-1).astype(jnp.float32)
    # pad so groups (and in scatter mode, the n chunks) divide evenly
    quantum = group_size * (n if mode == "scatter" else 1)
    pad = (-flat.shape[0]) % quantum
    if pad:
        flat = jnp.pad(flat, (0, pad))

    if mode == "gather":
        x2 = flat.reshape(-1, group_size)
        q, s = _quantize(x2, qmax)
        qg = lax.all_gather(_wire_encode(q, bits), axis_name)
        sg = lax.all_gather(s, axis_name)
        deq = _wire_decode(qg, bits).astype(jnp.float32) * sg
        out = jnp.sum(deq, axis=0) / n
    else:
        # chunk i of my gradient goes to member i; I become the reducer
        # for my own chunk index (quant_reduce.cu's partition layout)
        x3 = flat.reshape(n, -1, group_size)
        q, s = _quantize(x3, qmax)
        qt = lax.all_to_all(_wire_encode(q, bits), axis_name,
                            split_axis=0, concat_axis=0, tiled=False)
        st = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        # (n, groups, group): n members' versions of MY chunk
        chunk = jnp.sum(
            _wire_decode(qt, bits).astype(jnp.float32) * st, axis=0) / n
        # requantize the reduced chunk for the gather leg
        q2, s2 = _quantize(chunk, qmax)
        qg = lax.all_gather(_wire_encode(q2, bits), axis_name)
        sg = lax.all_gather(s2, axis_name)
        out = (_wire_decode(qg, bits).astype(jnp.float32) * sg)
    out = out.reshape(-1)
    if pad:
        out = out[:g.size]
    return out.astype(g.dtype).reshape(g.shape)


def quantized_pmean(tree: Any, axis_name: str, n: int, bits: int = 8,
                    group_size: int = DEFAULT_GROUP,
                    mode: str = "auto") -> Any:
    """Tree-wise quantized mean over a manual mesh axis (bits=0 =
    exact pmean on every leaf)."""
    if bits not in (8, 4, 0):
        raise ValueError(f"grad-reduce bits must be 8, 4 or 0, got {bits}")
    fn = functools.partial(quantized_pmean_leaf, axis_name=axis_name,
                           n=n, bits=bits, group_size=group_size,
                           mode=mode)
    return jax.tree.map(fn, tree)
