"""Host-level cross-slice gradient sync over DCN with slice-scoped
failure tolerance.

Multi-slice hierarchical DP, elastic-native: each ICI slice runs its own
jax world (per-slice rendezvous, master/rendezvous.py) and the gradient
sync is two-level — the in-slice mean rides XLA's implicit psum inside
the slice's program (trainer/train_step.py ``grad_fn``), the cross-slice
mean is exchanged HERE, through the master KV store, one post per slice
per step. Because the cross-slice leg is host-level, a dying slice
cannot wedge the survivors' collectives: the fleet degrades instead of
stalling.

Degraded mode (the failure-domain contract, ROADMAP item 5):

- The master's slice registry (``SliceStatusRequest``) names the
  PRESENT set each step. A slice that is draining or re-forming is
  absent; survivors renormalize the gradient mean over the slices that
  actually contributed and keep stepping.
- Every such step is a DEGRADED step: counted in
  ``dlrover_tpu_slice_degraded_steps_total{slice}``, reported to the
  master's goodput ledger (GlobalStepReport.degraded_steps), and
  flight-recorded at episode boundaries.
- The budget is ``Context.slice_absent_max_steps`` consecutive degraded
  steps. Past it the survivors HARD-STALL with a CRITICAL alert
  (``slice_absent_budget_blown`` flight event + the
  ``dlrover_tpu_slice_absent_stalled`` gauge) instead of silently
  training on a shrunken mean, and resume only when the fleet is whole.
- A re-formed slice catches up: peer restore puts it at the checkpointed
  step (checkpoint/peer_restore.py, same-slice donors first), then
  ``catch_up`` fetches the fleet-current state a surviving slice leader
  publishes through the rejoin handoff, so it resumes in lockstep.

Timing caveat (documented, not hidden): the per-step participant set is
"slices whose contribution arrived by the collector's deadline". A
contribution landing inside one collector's window but after another's
would momentarily diverge the replicas; the window is a full
``dcn_sync_timeout_s`` from roughly synchronized step starts, so the
race needs a straggler within epsilon of the deadline. A production DCN
transport would close it with a sequenced membership commit; the
control-plane shape (present set, renormalization, budget, catch-up) is
what this module contributes.

numpy + stdlib only (no jax): the caller flattens/unflattens its pytree;
this module moves ``List[np.ndarray]`` leaves, so lightweight test
workers exercise the real protocol without a jax runtime.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

# Legacy (pre-episode-hygiene) key names: used when the master's slice
# status carries no world epoch. Epoch-aware masters get generation-
# namespaced keys (``dcn/g<E>/...``) instead: every membership loss
# moves the fleet to a fresh namespace, so a stale previous-episode
# payload can never be re-adopted, and the kv store garbage-collects
# the superseded namespaces (master/kv_store.py).
GRAD_KEY_PREFIX = "dcn/grads/"
REJOIN_KEY = "dcn/rejoin"
STATE_KEY = "dcn/state"

_QUANT_GROUP = 256
_QMAX = 127
# below this many elements the quantization bookkeeping costs more than
# the wire savings (same rule as parallel/quant_collectives.py)
_MIN_QUANT_SIZE = 2048


# ---------------------------------------------------------------------------
# wire codec: header JSON line + concatenated leaf bytes
# ---------------------------------------------------------------------------


def _encode_leaf_exact(leaf: np.ndarray) -> Tuple[Dict[str, Any], bytes]:
    arr = np.ascontiguousarray(leaf)
    return ({"shape": list(arr.shape), "dtype": str(arr.dtype),
             "enc": "raw"}, arr.tobytes())


def _encode_leaf_quant(leaf: np.ndarray) -> Tuple[Dict[str, Any], bytes]:
    """Groupwise-symmetric int8 (the quant_collectives scheme, host
    side): codes + float32 scales per group. Non-float / tiny leaves
    ship exact."""
    arr = np.ascontiguousarray(leaf)
    if arr.dtype.kind != "f" or arr.size < _MIN_QUANT_SIZE:
        return _encode_leaf_exact(leaf)
    flat = arr.astype(np.float32).ravel()
    pad = (-flat.size) % _QUANT_GROUP
    if pad:
        flat = np.pad(flat, (0, pad))
    x2 = flat.reshape(-1, _QUANT_GROUP)
    absmax = np.abs(x2).max(axis=-1, keepdims=True)
    scale = absmax / _QMAX
    inv = np.where(scale > 0, 1.0 / np.where(scale > 0, scale, 1.0), 0.0)
    codes = np.clip(np.rint(x2 * inv), -_QMAX, _QMAX).astype(np.int8)
    header = {"shape": list(arr.shape), "dtype": str(arr.dtype),
              "enc": "q8", "pad": pad}
    return header, codes.tobytes() + scale.astype(np.float32).tobytes()


def _decode_leaf(meta: Dict[str, Any], raw: bytes) -> np.ndarray:
    shape = tuple(int(s) for s in meta["shape"])
    dtype = np.dtype(meta["dtype"])
    if meta.get("enc") == "q8":
        pad = int(meta.get("pad", 0))
        n = int(np.prod(shape, dtype=np.int64)) + pad
        groups = n // _QUANT_GROUP
        codes = np.frombuffer(raw, np.int8, count=n).reshape(
            groups, _QUANT_GROUP)
        scale = np.frombuffer(raw, np.float32, count=groups,
                              offset=n).reshape(groups, 1)
        flat = codes.astype(np.float32) * scale
        flat = flat.reshape(-1)
        if pad:
            flat = flat[:-pad]
        return flat.astype(dtype).reshape(shape)
    # the copy matters: np.frombuffer views are read-only and may be
    # misaligned for device_put zero-copy (the PR 7 lesson)
    return np.frombuffer(raw, dtype).reshape(shape).copy()


def encode_leaves(leaves: List[np.ndarray], step: int,
                  quant_bits: int = 0,
                  extra: Optional[Dict[str, Any]] = None) -> bytes:
    """``leaves`` → one payload: header JSON line, then leaf bytes."""
    encode = _encode_leaf_quant if quant_bits == 8 else _encode_leaf_exact
    if quant_bits not in (0, 8):
        raise ValueError(f"dcn sync quant bits must be 0 or 8, "
                         f"got {quant_bits}")
    metas: List[Dict[str, Any]] = []
    blobs: List[bytes] = []
    for leaf in leaves:
        meta, blob = encode(np.asarray(leaf))
        meta["bytes"] = len(blob)
        metas.append(meta)
        blobs.append(blob)
    header = {"step": int(step), "leaves": metas}
    if extra:
        header.update(extra)
    return json.dumps(header).encode() + b"\n" + b"".join(blobs)


def decode_payload(data: bytes
                   ) -> Optional[Tuple[Dict[str, Any],
                                       List[np.ndarray]]]:
    """Payload → (header, leaves); None on empty/torn bytes (a reader
    must treat garbage as absence, never crash the step loop)."""
    if not data:
        return None
    try:
        head_raw, _, body = data.partition(b"\n")
        header = json.loads(head_raw)
        leaves = []
        offset = 0
        for meta in header.get("leaves", ()):
            size = int(meta["bytes"])
            leaves.append(_decode_leaf(meta, body[offset:offset + size]))
            offset += size
        return header, leaves
    except Exception:  # noqa: BLE001 — torn/alien payloads read as absent
        logger.warning("undecodable DCN sync payload (%d bytes)",
                       len(data))
        return None


def peek_step(data: bytes) -> int:
    """The header step of a payload without decoding leaves (-1 on
    garbage) — the collector's cheap freshness probe."""
    if not data:
        return -1
    try:
        head_raw, _, _ = data.partition(b"\n")
        return int(json.loads(head_raw).get("step", -1))
    except Exception:  # noqa: BLE001
        return -1


# ---------------------------------------------------------------------------
# the sync
# ---------------------------------------------------------------------------


class SliceGradSync:
    """One slice's participant in the cross-slice gradient exchange.

    ``client`` needs ``kv_set``/``kv_get``/``get_slice_status`` (the
    MasterClient surface). ``is_leader`` marks the slice's process 0 —
    the only rank that posts payloads (every rank collects, so all
    ranks of a slice compute the identical fleet mean)."""

    def __init__(self, client, slice_id: int, is_leader: bool = True,
                 abort_fn: Optional[Callable[[], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
        from dlrover_tpu import obs

        self._client = client
        self.slice_id = int(slice_id)
        self.is_leader = bool(is_leader)
        self._abort = abort_fn or (lambda: False)
        self._clock = clock
        # consecutive degraded steps of the CURRENT absence episode —
        # the budget counter; resets the moment the fleet is whole
        self.consecutive_degraded = 0
        # total degraded steps taken since construction, and the count
        # not yet shipped on a step report (elastic_loop drains it)
        self.degraded_total = 0
        self.degraded_unreported = 0
        self._budget_blown_logged = False
        # the fleet size the master last reported: a failed status RPC
        # (master outage) must still count local-only steps as DEGRADED
        # — syncing with nobody IS the shrunken mean the budget bounds
        self._last_known_total = 0
        # the world epoch the master last reported (-1 = unknown /
        # legacy master): namespaces every dcn/ key so payloads from a
        # previous membership episode are unreachable by construction
        self._epoch = -1
        # per-step cross-slice timing marks for the last reduce() —
        # steptrace evidence, consumed via info["trace"]
        # graftlint: ephemeral(per-step telemetry, rebuilt every reduce)
        self._last_peer_obs: Dict[int, float] = {}
        registry = obs.get_registry()
        self._degraded_counter = registry.counter(
            "dlrover_tpu_slice_degraded_steps_total",
            "Steps this slice took with the gradient mean renormalized "
            "over present slices (a peer slice was absent)",
            labelnames=("slice",))
        self._stalled_gauge = registry.gauge(
            "dlrover_tpu_slice_absent_stalled",
            "1 while this slice is hard-stalled: the degraded-step "
            "budget (slice_absent_max_steps) is blown and a peer slice "
            "is still absent")
        self._stalled_gauge.set(0)

    # -- master status ------------------------------------------------------
    def _status(self) -> Dict[str, Any]:
        try:
            status = self._client.get_slice_status() or {}
        except Exception:  # noqa: BLE001 — a master blip must not kill
            # the step; syncing with nobody is the safe degradation
            logger.warning("slice status unavailable; treating the "
                           "fleet as this slice only for this step")
            # the master may have MOVED (standby promotion — workers
            # are deliberately not respawned): re-dial from the
            # bootstrap file so the degraded episode ends with the
            # promotion instead of stalling out the absent budget
            try:
                reresolve = getattr(self._client, "reresolve_if_moved",
                                    None)
                if reresolve is not None:
                    reresolve()
            except Exception:  # noqa: BLE001 — next step retries
                pass
            return {}
        epoch = status.get("epoch")
        if epoch is not None:
            try:
                self._epoch = int(epoch)
            except (TypeError, ValueError):
                pass
        return status

    @staticmethod
    def _formed_slices(status: Dict[str, Any]) -> Dict[int, bool]:
        out: Dict[int, bool] = {}
        for sid, info in (status.get("slices") or {}).items():
            try:
                out[int(sid)] = bool(info.get("formed"))
            except (TypeError, ValueError, AttributeError):
                continue
        return out

    @property
    def world_epoch(self) -> int:
        """The membership episode the master last reported (-1 =
        unknown / legacy master) — steptrace records group under it."""
        return self._epoch

    # -- keys ---------------------------------------------------------------
    def _ns(self, suffix: str) -> str:
        """Epoch-namespaced key (legacy bare name when the master never
        reported an epoch). All slices read the epoch from the same
        master status, so writers and readers of one episode agree."""
        if self._epoch < 0:
            return f"dcn/{suffix}"
        return f"dcn/g{self._epoch}/{suffix}"

    def _grad_key(self, slice_id: int) -> str:
        return self._ns(f"grads/{slice_id}")

    def _rejoin_key(self) -> str:
        return self._ns("rejoin")

    def _state_key(self) -> str:
        return self._ns("state")

    # -- rejoin handoff (survivor side) -------------------------------------
    def _service_rejoin(self, step: int,
                        state_leaves_fn: Optional[Callable[[], list]],
                        formed: Dict[int, bool]) -> None:
        """A SURVIVING slice leader answers a pending rejoin request by
        publishing its CURRENT state (the post-update state of step
        ``step - 1``) so the re-formed slice resumes in lockstep
        instead of N checkpoint-intervals behind. The request is read
        FIRST and its slice excluded from the leader election — by the
        time a survivor looks, the rejoiner's slice is formed again and
        may well be the lowest id (it must never be its own donor)."""
        if state_leaves_fn is None or not self.is_leader:
            return
        try:
            raw = self._client.kv_get(self._rejoin_key())
        except Exception:  # noqa: BLE001 — next step retries
            return
        if not raw:
            return
        try:
            request = json.loads(raw)
            from_step = int(request.get("step", -1))
            asking = int(request.get("slice", -1))
            token = str(request.get("token", ""))
        except (ValueError, TypeError):
            # garbage request: clear it so it cannot wedge the channel
            self._try_kv_set(self._rejoin_key(), b"")
            return
        if asking == self.slice_id:
            return          # our own pending request — not our job
        active = sorted(sid for sid, ok in formed.items()
                        if ok and sid != asking)
        if not active or active[0] != self.slice_id:
            return
        if from_step >= step - 1:
            # the rejoiner is already current; just clear the request
            self._try_kv_set(self._rejoin_key(), b"")
            return
        from dlrover_tpu import obs

        # the request token rides in the payload header: the rejoiner
        # accepts ONLY the answer to ITS request, so a stale dcn/state
        # from a previous handoff episode can never be adopted
        payload = encode_leaves(state_leaves_fn(), step - 1,
                                extra={"kind": "state",
                                       "from_slice": self.slice_id,
                                       "token": token})
        if self._try_kv_set(self._state_key(), payload):
            self._try_kv_set(self._rejoin_key(), b"")
            logger.warning(
                "slice %d: published fleet state @ step %d for "
                "re-formed slice %d (%d bytes)", self.slice_id,
                step - 1, asking, len(payload))
            obs.get_flight_recorder().record_event(
                "slice_state_handoff", from_slice=self.slice_id,
                to_slice=asking, step=step - 1, bytes=len(payload))

    def _try_kv_set(self, key: str, value: bytes) -> bool:
        try:
            self._client.kv_set(key, value)
            return True
        except Exception:  # noqa: BLE001
            logger.warning("kv_set %s failed", key)
            return False

    # -- rejoin catch-up (re-formed slice side) -----------------------------
    def catch_up(self, start_step: int, timeout_s: Optional[float] = None
                 ) -> Optional[Tuple[List[np.ndarray], int]]:
        """After a peer/Orbax restore at ``start_step``: when the fleet
        is ahead, fetch the state a surviving slice leader publishes and
        return (state leaves, fleet step) — or None when the fleet is
        not ahead (fresh job, lockstep restore) or nobody answered
        inside the window (train from the restored step; the survivors'
        degraded accounting keeps the gap visible)."""
        from dlrover_tpu import obs
        from dlrover_tpu.common.config import Context

        status = self._status()
        fleet_step = int(status.get("fleet_step", 0) or 0)
        formed = self._formed_slices(status)
        others_formed = any(ok for sid, ok in formed.items()
                            if sid != self.slice_id)
        if fleet_step <= start_step or not others_formed:
            return None
        # a fresh token per request (echoed in the answer for
        # debuggability); staleness is gated below on the header STEP —
        # a token check would only work for the leader, and every rank
        # of the slice must adopt the same payload
        import os as _os

        token = _os.urandom(8).hex()
        if self.is_leader:
            self._try_kv_set(self._rejoin_key(), json.dumps(
                {"slice": self.slice_id, "step": start_step,
                 "token": token}).encode())
        logger.warning(
            "slice %d re-formed at step %d but the fleet is at %d: "
            "requesting a state handoff", self.slice_id, start_step,
            fleet_step)
        ctx = Context.singleton()
        budget = (timeout_s if timeout_s is not None
                  else 2.0 * ctx.dcn_sync_timeout_s)
        deadline = self._clock() + budget
        # the answer must carry the fleet head or newer: dcn/state is
        # never cleared, so a payload left by a PREVIOUS handoff
        # episode (step < the fleet head we just observed) must be
        # ignored, or this slice would adopt a months-old state and
        # permanently diverge from the survivors
        min_step = max(fleet_step, start_step + 1)
        last_repost = self._clock()
        while self._clock() < deadline and not self._abort():
            # keep the request alive: a publisher that answered with a
            # state just under min_step consumed the request — re-post
            # so the NEXT survivor step publishes a fresh-enough one
            if (self.is_leader
                    and self._clock() - last_repost >= 1.0):
                last_repost = self._clock()
                try:
                    if not self._client.kv_get(self._rejoin_key()):
                        self._try_kv_set(self._rejoin_key(), json.dumps(
                            {"slice": self.slice_id,
                             "step": start_step,
                             "token": token}).encode())
                except Exception:  # noqa: BLE001 — next tick retries
                    pass
            try:
                raw = self._client.kv_get(self._state_key())
            except Exception:  # noqa: BLE001
                raw = b""
            if peek_step(raw) >= min_step:
                decoded = decode_payload(raw)
                if decoded is not None:
                    header, leaves = decoded
                    step = int(header.get("step", start_step))
                    obs.get_flight_recorder().record_event(
                        "slice_rejoin_catchup", slice=self.slice_id,
                        restored_step=start_step, fleet_step=step,
                        bytes=len(raw))
                    logger.warning(
                        "slice %d: caught up to fleet step %d via the "
                        "DCN state handoff", self.slice_id, step)
                    return leaves, step
            time.sleep(ctx.dcn_sync_poll_s)
        logger.error(
            "slice %d: no state handoff arrived within %.0fs; resuming "
            "from the restored step %d (the fleet's degraded "
            "accounting keeps the gap visible)", self.slice_id, budget,
            start_step)
        return None

    # -- the per-step exchange ----------------------------------------------
    def reduce(self, leaves: List[np.ndarray], step: int,
               state_leaves_fn: Optional[Callable[[], list]] = None,
               ) -> Tuple[List[np.ndarray], Dict[str, Any]]:
        """Exchange this slice's in-slice-mean gradient ``leaves`` for
        step ``step``; returns (fleet-mean leaves over PRESENT slices,
        info). ``state_leaves_fn`` lets the fleet leader answer rejoin
        handoffs with the current pre-update state."""
        from dlrover_tpu.common.config import Context

        t_ready = self._clock()   # gradients in hand, exchange begins
        self._last_peer_obs = {}
        ctx = Context.singleton()
        status = self._status()
        formed = self._formed_slices(status)
        total = max(len(formed),
                    int(status.get("total", len(formed)) or 0))
        info: Dict[str, Any] = {"step": step, "present": [self.slice_id],
                                "absent": [], "total": total,
                                "degraded": False, "stalled_s": 0.0}
        if total <= 1 or not formed:
            if status:
                # the master genuinely says single-slice fleet:
                # nothing to exchange, nothing to degrade against
                self._last_known_total = max(1, total)
                self._note_whole()
            elif self._last_known_total > 1:
                # status unavailable (master blip/outage) in a fleet we
                # KNOW is multi-slice: this local-only step is exactly
                # the shrunken mean the degraded budget exists to bound
                # — and the budget applies here too (a long outage must
                # not buy unbounded solo training)
                if (self.consecutive_degraded
                        >= max(1, ctx.slice_absent_max_steps)):
                    info["stalled_s"] = self._stall_until_whole(
                        step, state_leaves_fn)
                    if not self._abort():
                        return self.reduce(leaves, step,
                                           state_leaves_fn)
                info.update(total=self._last_known_total,
                            degraded=True)
                self._note_degraded(step, ["unknown"],
                                    self._last_known_total)
            now = self._clock()
            info["trace"] = {"grads_ready": t_ready, "local_post": t_ready,
                             "collect_done": now, "peers": {}}
            return leaves, info
        self._last_known_total = total
        formed.setdefault(self.slice_id, True)
        # budget check FIRST: a blown budget means no more renormalized
        # steps — stall until the fleet is whole (or we are told to stop)
        absent_now = sorted(sid for sid, ok in formed.items() if not ok)
        if (absent_now
                and self.consecutive_degraded
                >= max(1, ctx.slice_absent_max_steps)):
            stalled = self._stall_until_whole(step, state_leaves_fn)
            info["stalled_s"] = stalled
            status = self._status()
            formed = self._formed_slices(status)
            formed.setdefault(self.slice_id, True)
        self._service_rejoin(step, state_leaves_fn, formed)
        if self.is_leader:
            self._try_kv_set(self._grad_key(self.slice_id),
                             encode_leaves(
                                 leaves, step,
                                 quant_bits=ctx.dcn_sync_quant_bits))
        t_post = self._clock()    # local contribution on the wire
        contributions: List[List[np.ndarray]] = [
            [np.asarray(leaf, np.float32) for leaf in leaves]]
        expected = sorted(sid for sid, ok in formed.items()
                          if ok and sid != self.slice_id)
        collected, missing = self._collect(expected, step, ctx)
        for peer_leaves in collected.values():
            contributions.append(peer_leaves)
        n = len(contributions)
        reduced = [
            (sum(c[i] for c in contributions) / n).astype(
                np.asarray(leaves[i]).dtype)
            for i in range(len(leaves))
        ] if n > 1 else list(leaves)
        present = sorted([self.slice_id] + list(collected))
        absent = sorted(set(sid for sid in formed if sid not in present)
                        | set(missing))
        info.update(present=present, absent=absent,
                    degraded=len(present) < total)
        # the steptrace decomposition: grads-ready → local-post →
        # per-peer-header-observed → last-peer (collect done); clock()
        # reads only — nothing here blocks or takes a lock
        info["trace"] = {"grads_ready": t_ready, "local_post": t_post,
                         "collect_done": self._clock(),
                         "peers": dict(self._last_peer_obs)}
        if info["degraded"]:
            self._note_degraded(step, absent, total)
        else:
            self._note_whole()
        return reduced, info

    def _collect(self, expected: List[int], step: int, ctx
                 ) -> Tuple[Dict[int, List[np.ndarray]], List[int]]:
        """Poll the formed peers' grad keys until each posts for
        ``step`` or the deadline lands; a peer that un-forms mid-wait
        (the master reaped it) is dropped from the expected set."""
        collected: Dict[int, List[np.ndarray]] = {}
        if not expected:
            return collected, []
        pending = set(expected)
        deadline = self._clock() + ctx.dcn_sync_timeout_s
        last_status_check = self._clock()
        while pending and self._clock() < deadline and not self._abort():
            for sid in sorted(pending):
                try:
                    raw = self._client.kv_get(self._grad_key(sid))
                except Exception:  # noqa: BLE001 — master blip
                    continue
                posted = peek_step(raw)
                if posted == step:
                    decoded = decode_payload(raw)
                    if decoded is not None:
                        # steptrace: when this peer's header for the
                        # step was first observed (the join's input edge)
                        self._last_peer_obs[sid] = self._clock()
                        collected[sid] = decoded[1]
                        pending.discard(sid)
                elif posted > step:
                    # the peer moved past us: we were treated absent
                    # (e.g. resumed behind the fleet) — its old grads
                    # must not be averaged into this step
                    logger.error(
                        "slice %d is at step %d but peer slice %d "
                        "already synced step %d; treating it absent",
                        self.slice_id, step, sid, posted)
                    pending.discard(sid)
            if pending:
                now = self._clock()
                if now - last_status_check >= 1.0:
                    # mid-wait membership change: a peer the master no
                    # longer calls formed will never post — stop waiting
                    last_status_check = now
                    formed = self._formed_slices(self._status())
                    for sid in list(pending):
                        if not formed.get(sid, False):
                            logger.warning(
                                "peer slice %d un-formed mid-step; "
                                "dropping it from step %d's sync",
                                sid, step)
                            pending.discard(sid)
                time.sleep(ctx.dcn_sync_poll_s)
        for sid in sorted(pending):
            logger.warning(
                "formed peer slice %d posted nothing for step %d "
                "within %.0fs; treating it absent for this step",
                sid, step, ctx.dcn_sync_timeout_s)
        return collected, sorted(pending)

    # -- degraded bookkeeping -----------------------------------------------
    def _note_degraded(self, step: int, absent: List[int],
                       total: int) -> None:
        from dlrover_tpu import obs

        first = self.consecutive_degraded == 0
        self.consecutive_degraded += 1
        self.degraded_total += 1
        self.degraded_unreported += 1
        self._degraded_counter.labels(slice=str(self.slice_id)).inc()
        if first:
            logger.warning(
                "DEGRADED step %d: slice(s) %s absent — gradient mean "
                "renormalized over %d/%d slices (budget %d steps)",
                step, absent, total - len(absent), total,
                self.consecutive_degraded)
            obs.get_flight_recorder().record_event(
                "slice_degraded", slice=self.slice_id, step=step,
                absent=absent, total=total)

    def _note_whole(self) -> None:
        if self.consecutive_degraded:
            logger.info(
                "fleet whole again after %d degraded step(s)",
                self.consecutive_degraded)
        self.consecutive_degraded = 0
        self._budget_blown_logged = False

    def _stall_until_whole(self, step: int,
                           state_leaves_fn) -> float:
        """The budget is blown: refuse further renormalized steps.
        CRITICAL alert once, then block until every known slice is
        formed again — servicing rejoin handoffs meanwhile so the
        stall can actually END (the re-formed slice needs the state
        handoff before it can participate)."""
        from dlrover_tpu import obs
        from dlrover_tpu.common.config import Context

        ctx = Context.singleton()
        if not self._budget_blown_logged:
            self._budget_blown_logged = True
            logger.critical(
                "slice-absent budget BLOWN: %d consecutive degraded "
                "steps (slice_absent_max_steps=%d) and a slice is "
                "still absent — HARD-STALLING at step %d until the "
                "fleet is whole (silently training on a shrunken mean "
                "is not an option past the budget)",
                self.consecutive_degraded, ctx.slice_absent_max_steps,
                step)
            obs.get_flight_recorder().record_event(
                "slice_absent_budget_blown", slice=self.slice_id,
                step=step, degraded_steps=self.consecutive_degraded,
                budget=ctx.slice_absent_max_steps)
        self._stalled_gauge.set(1)
        start = self._clock()
        try:
            while not self._abort():
                status = self._status()
                formed = self._formed_slices(status)
                if formed and all(formed.values()):
                    self._note_whole()
                    logger.warning(
                        "fleet whole again after a %.1fs hard stall; "
                        "resuming", self._clock() - start)
                    break
                self._service_rejoin(step, state_leaves_fn, formed)
                time.sleep(max(ctx.dcn_sync_poll_s, 0.2))
        finally:
            self._stalled_gauge.set(0)
        return self._clock() - start

    def drain_unreported(self) -> int:
        """Degraded steps taken since the last call — the step report's
        ``degraded_steps`` field (elastic_loop drains at report
        intervals)."""
        count = self.degraded_unreported
        self.degraded_unreported = 0
        return count
