"""Mixture-of-Experts with expert parallelism.

Capability parity: atorch modules/moe/ — `MOELayer` (moe_layer.py:161),
`Experts` (:116), top-k gating (topk_gating.py), switch gating
(switch_gating.py), `_AllToAll` autograd (:87), expert process groups
(:29).

TPU re-design: the classic capacity-based dispatch/combine einsum
formulation (Mesh-TensorFlow / Switch Transformer lineage): the router
builds a dispatch mask (tokens → expert capacity slots) and combine
weights; expert parameters carry an "expert" logical axis mapped to the
`expert` mesh axis, and XLA inserts the all-to-all when the dispatch
einsum crosses the expert sharding — no explicit _AllToAll autograd
function needed (its transpose falls out of autodiff).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    hidden_size: int = 512
    expert_intermediate: int = 1024
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    jitter_noise: float = 0.0       # router input jitter (switch-style)
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


def _capacity(tokens_per_group: int, num_experts: int,
              capacity_factor: float, min_capacity: int) -> int:
    capacity = int(tokens_per_group * capacity_factor / num_experts)
    return max(capacity, min_capacity)


def top_k_gating(
    router_logits: jax.Array,     # (G, S, E) groups × tokens × experts
    top_k: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Capacity-based top-k routing.

    Returns (dispatch_mask (G,S,E,C) bool, combine_weights (G,S,E,C),
    aux_loss). Tokens over an expert's capacity are dropped (the standard
    TPU MoE contract; the residual path keeps them alive).
    """
    groups, seq, num_experts = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    # load-balancing aux loss (Switch eq. 4): E * Σ_e f_e · P_e
    top1 = jnp.argmax(probs, axis=-1)
    f = jnp.mean(jax.nn.one_hot(top1, num_experts), axis=1)   # (G, E)
    p = jnp.mean(probs, axis=1)                               # (G, E)
    aux_loss = num_experts * jnp.mean(jnp.sum(f * p, axis=-1))

    # iteratively take the k best experts per token
    dispatch = jnp.zeros((groups, seq, num_experts, capacity),
                         dtype=jnp.bool_)
    combine = jnp.zeros((groups, seq, num_experts, capacity),
                        dtype=jnp.float32)
    remaining = probs
    # slots already used per expert, carried across the k rounds
    fill = jnp.zeros((groups, num_experts), dtype=jnp.int32)
    for _ in range(top_k):
        expert_idx = jnp.argmax(remaining, axis=-1)           # (G, S)
        gate = jnp.take_along_axis(remaining, expert_idx[..., None],
                                   axis=-1)[..., 0]           # (G, S)
        onehot = jax.nn.one_hot(expert_idx, num_experts,
                                dtype=jnp.int32)              # (G, S, E)
        # position of each token in its expert's queue this round
        position = jnp.cumsum(onehot, axis=1) - 1 + fill[:, None, :]
        position = jnp.sum(position * onehot, axis=-1)        # (G, S)
        within = position < capacity
        slot_onehot = (
            jax.nn.one_hot(position, capacity, dtype=jnp.float32)
            * (onehot.sum(-1) * within)[..., None])           # (G, S, C)
        this_dispatch = (onehot[..., None] *
                         slot_onehot[:, :, None, :]).astype(jnp.bool_)
        dispatch = dispatch | this_dispatch
        combine = combine + this_dispatch * gate[..., None, None]
        fill = fill + jnp.sum(onehot * within[..., None].astype(jnp.int32),
                              axis=1)
        remaining = remaining * (1.0 - onehot.astype(remaining.dtype))
    # renormalize combine weights over the selected experts
    denom = jnp.sum(combine, axis=(2, 3), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux_loss


class ExpertMLP(nn.Module):
    """E parallel feed-forward experts; params carry the 'expert' logical
    axis so EP shards them (atorch Experts analog)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # x: (E, C_total, H)
        cfg = self.cfg
        wi = self.param(
            "wi",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "embed", "mlp")),
            (cfg.num_experts, cfg.hidden_size, cfg.expert_intermediate),
            cfg.param_dtype,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("expert", "mlp", "embed")),
            (cfg.num_experts, cfg.expert_intermediate, cfg.hidden_size),
            cfg.param_dtype,
        )
        x = x.astype(cfg.dtype)
        h = jnp.einsum("ech,ehm->ecm", x, wi.astype(cfg.dtype))
        h = nn.gelu(h)
        return jnp.einsum("ecm,emh->ech", h, wo.astype(cfg.dtype))


class MoELayer(nn.Module):
    """Drop-in MLP replacement: (..., S, H) → (..., S, H) + aux loss via
    `self.sow('losses', 'moe_aux_loss', ...)` (atorch MOELayer analog)."""

    cfg: MoEConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        orig_shape = x.shape
        hidden = orig_shape[-1]
        # flatten leading dims into routing groups
        x = x.reshape((-1,) + orig_shape[-2:])    # (G, S, H)
        groups, seq, _ = x.shape

        router = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "expert")),
            (hidden, cfg.num_experts),
            jnp.float32,
        )
        router_in = x.astype(jnp.float32)
        if cfg.jitter_noise > 0 and not self.deterministic:
            rng = self.make_rng("gating")
            router_in = router_in * jax.random.uniform(
                rng, router_in.shape, minval=1.0 - cfg.jitter_noise,
                maxval=1.0 + cfg.jitter_noise)
        logits = router_in @ router                # (G, S, E)

        capacity = _capacity(seq, cfg.num_experts,
                             cfg.capacity_factor if not self.deterministic
                             else cfg.eval_capacity_factor,
                             cfg.min_capacity)
        capacity = min(capacity, seq)
        dispatch, combine, aux_loss = top_k_gating(
            logits, cfg.top_k, capacity)
        self.sow("losses", "moe_aux_loss", cfg.aux_loss_weight * aux_loss)

        # dispatch: (G,S,E,C) × (G,S,H) → (E, G*C, H); the contraction
        # crossing the expert-sharded dim is where XLA places the
        # all-to-all when E is sharded over the expert mesh axis
        expert_in = jnp.einsum("gsec,gsh->egch",
                               dispatch.astype(x.dtype), x)
        expert_in = expert_in.reshape(cfg.num_experts,
                                      groups * capacity, hidden)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", None, "embed"))
        expert_out = ExpertMLP(cfg)(expert_in)
        expert_out = expert_out.reshape(cfg.num_experts, groups, capacity,
                                        hidden)
        out = jnp.einsum("gsec,egch->gsh",
                         combine.astype(expert_out.dtype), expert_out)
        return out.reshape(orig_shape).astype(x.dtype)


def moe_aux_loss(variables) -> jax.Array:
    """Collect sown aux losses from a model's 'losses' collection."""
    losses = variables.get("losses", {})
    total = 0.0
    for leaf in jax.tree.leaves(losses):
        total = total + jnp.sum(leaf)
    return total
