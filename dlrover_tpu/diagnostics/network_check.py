"""Network check: 2-round paired ICI/DCN probe + straggler detection.

Capability parity: dlrover's `--network-check` path — the agent runs a
diagnostic task before training (elastic_agent/torch/training.py:681-874
NetworkCheckElasticAgent; probe task trainer/torch/run_network_check.py:30-92
does matmul + repeated allgather and writes elapsed time to a file); the
master groups nodes in pairs (round 0 adjacent, round 1
fastest-with-slowest), isolates nodes that fail BOTH rounds as faulty, and
flags elapsed > 2×median as stragglers (rdzv_manager.py:299-461).

TPU re-design: the probe is a fresh JAX subprocess per round (a JAX process
can only initialize one distributed runtime, and each round re-forms the
group). Within the pair group it runs a bf16 matmul burst (MXU sanity) and
repeated `jax.lax.all_gather` over every chip of the pair (ICI/DCN sanity)
under `shard_map`, then writes elapsed seconds to a result file the agent
reports to the master.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.bootstrap import publish_or_wait_coordinator
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import default_logger as logger

_RESULT_FILE_ENV = "DLROVER_TPU_NC_RESULT_FILE"
_MATMUL_SIZE = 4096
_ALLGATHER_FLOATS = 1 << 20
_ROUNDS = 2
_REPEATS = 10


# ---------------------------------------------------------------------------
# Probe subprocess
# ---------------------------------------------------------------------------


def probe_main() -> int:
    """Entry for `python -m dlrover_tpu.diagnostics.network_check`.

    Initializes jax.distributed within the pair group from the agent env
    contract, runs the probe, writes `{"elapsed": s}` to the result file.
    """
    result_file = os.environ[_RESULT_FILE_ENV]
    from dlrover_tpu.agent.elastic_agent import init_distributed

    init_distributed()
    import jax
    import jax.numpy as jnp

    # The matmul burst is MXU sanity — on the CPU backend (tests, dev
    # boxes) a 4096^3 bf16 burst is ~400 GFLOPs of pure execution that
    # starves a loaded host and flakes the pair's coordination-service
    # deadlines; small there, full-size on real chips.
    size = _MATMUL_SIZE if jax.default_backend() == "tpu" else 512
    x = jnp.ones((size, size), jnp.bfloat16)

    @jax.jit
    def matmul_burst(x):
        for _ in range(3):
            x = jnp.tanh(x @ x * 1e-4)
        return x

    n = jax.device_count()
    gather_sum = None
    data = None
    if n > 1:
        from jax.sharding import Mesh, PartitionSpec as P

        from dlrover_tpu.common.jax_compat import shard_map

        mesh = Mesh(jax.devices(), ("probe",))
        data = jnp.ones((n, _ALLGATHER_FLOATS), jnp.float32)

        @jax.jit
        def gather_sum(arr):
            def inner(block):
                gathered = jax.lax.all_gather(block, "probe")
                return jnp.sum(gathered, dtype=jnp.float32)[None]

            return shard_map(
                inner, mesh=mesh, in_specs=P("probe"), out_specs=P("probe")
            )(arr)

    # compile OUTSIDE the timed window: the elapsed that feeds straggler
    # detection (2x median) must compare EXECUTION, and a peer stuck in
    # a cold compile mid-collective is what tripped the coordination
    # service's deadline under load (round-3 flake)
    matmul_exec = matmul_burst.lower(x).compile()
    gather_exec = (gather_sum.lower(data).compile()
                   if gather_sum is not None else None)

    t0 = time.perf_counter()
    jax.block_until_ready(matmul_exec(x))
    if gather_exec is not None:
        # ICI/DCN sanity: repeated all-gather across the group's chips
        for _ in range(_REPEATS):
            out = gather_exec(data)
        jax.block_until_ready(out)
        expected = float(n * _ALLGATHER_FLOATS)
        if abs(float(out[0]) - expected) > 1e-3 * expected:
            raise RuntimeError(
                f"allgather result {float(out[0])} != {expected}"
            )
    elapsed = time.perf_counter() - t0
    with open(result_file, "w") as f:
        json.dump({"elapsed": elapsed}, f)
    return 0


# ---------------------------------------------------------------------------
# Agent-side driver
# ---------------------------------------------------------------------------


def _probe_round(client: MasterClient, devices_per_node: int,
                 timeout_s: float) -> Tuple[bool, float]:
    """Join one NETWORK_CHECK round, run the probe in the pair group,
    return (normal, elapsed)."""
    rdzv = RendezvousName.NETWORK_CHECK
    client.join_rendezvous(devices_per_node, rdzv)
    deadline = time.time() + timeout_s
    while True:
        rdzv_round, group, world = client.get_comm_world(rdzv)
        if world and client.node_rank in world:
            break
        if time.time() > deadline:
            # withdraw the stale join: a late partner must not complete
            # this round against a peer that already gave up (it would
            # hang waiting for a coordinator that never publishes).
            # Best-effort: a master hiccup here must stay a round
            # failure, not escalate into an exception that fails the
            # whole health check.
            try:
                client.leave_rendezvous(rdzv)
            except Exception:
                logger.warning("network check: leave_rendezvous failed; "
                               "continuing with round failure",
                               exc_info=True)
            return False, 0.0
        time.sleep(0.5)

    ranks = sorted(world)
    process_id = ranks.index(client.node_rank)
    try:
        coord = publish_or_wait_coordinator(
            client, f"coord/{rdzv}/{rdzv_round}/{group}", process_id,
            timeout_s,
        )
    except TimeoutError:
        # the pair's rank 0 never published (it may have abandoned the
        # round under load): this ROUND failed for us; the verdict layer
        # decides faultiness from both rounds
        logger.warning("network check: no coordinator for round %d "
                       "group %d; counting the round as failed",
                       rdzv_round, group)
        return False, 0.0

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        result_file = f.name
    env = dict(os.environ)
    env.update({
        NodeEnv.WORLD_SIZE: str(len(ranks)),
        NodeEnv.PROCESS_ID: str(process_id),
        NodeEnv.COORDINATOR_ADDR: coord,
        _RESULT_FILE_ENV: result_file,
    })
    # Round 1 re-runs the same probe program in a fresh process; a shared
    # persistent compile cache lets it skip the cold compile that makes a
    # loaded 1-core host starve the coordination-service deadline.
    # per-user cache dir (uid, not getpass: containers with no passwd
    # entry for an arbitrary uid raise KeyError from getpass.getuser())
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(tempfile.gettempdir(),
                                f"dlrover_tpu_nc_cache_{os.getuid()}"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "dlrover_tpu.diagnostics.network_check"],
            env=env, timeout=timeout_s,
        )
        normal = proc.returncode == 0
    except subprocess.TimeoutExpired:
        normal = False
    elapsed = time.perf_counter() - t0
    try:
        with open(result_file) as f:
            elapsed = json.load(f)["elapsed"]
    except Exception:
        normal = False
    finally:
        try:
            os.unlink(result_file)
        except OSError:
            pass
    return normal, elapsed


def run_network_check(client: MasterClient, devices_per_node: int = 1,
                      exclude_straggler: bool = False,
                      timeout_s: float = 300.0) -> bool:
    """Run the 2-round probe and ask the master for the verdict. Returns
    whether this node may join training (reference: training.py:681-733)."""
    for check_round in range(_ROUNDS):
        normal, elapsed = _probe_round(client, devices_per_node, timeout_s)
        logger.info("network check round %d: normal=%s elapsed=%.2fs",
                    check_round, normal, elapsed)
        client.report_network_status(normal, elapsed)
    verdict = client.get_network_check_verdict()
    if not verdict.normal:
        logger.error("network check: this node is FAULTY (%s)",
                     verdict.reason)
        return False
    if verdict.is_straggler:
        logger.warning("network check: this node is a STRAGGLER")
        if exclude_straggler:
            return False
    return True


if __name__ == "__main__":
    raise SystemExit(probe_main())
