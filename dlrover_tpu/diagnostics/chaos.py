"""Scriptable fault injection for chaos testing.

Capability parity: the reference ships chaosblade-driven chaos jobs
(/root/reference/examples/pytorch/mnist/start_chaos.sh +
chaos_test_job.yaml — kill/cpu-load a chosen pod while the job runs) to
demonstrate recovery. TPU re-design: no external agent — a worker-side
hook the train loop polls each step, scripted through one env var, so a
chaos run is just a normal job launch with `DLROVER_TPU_CHAOS` set on
(or forwarded to) the chosen node.

Spec grammar (semicolon-separated faults):

    DLROVER_TPU_CHAOS="action:role:rank@step[:duration]"

    kill:worker:0@5        SIGKILL worker rank 0 when it reaches step 5
    hang:worker:1@3:120    rank 1 blocks 120 s at step 3 (hang detector
                           / straggler territory)
    slow:worker:2@4:0.5    rank 2 sleeps 0.5 s EVERY step from step 4 on
                           (a straggler the network-check/speed paths
                           should flag)
    kill:master:0@5        SIGKILL the job MASTER when any worker reports
                           step 5 (the servicer feeds worker
                           GlobalStepReports to a master-side injector) —
                           exercises crash-consistent state recovery +
                           agent reconnection, and (with a hot standby
                           watching, master/standby.py) the promotion
                           path (docs/fault_tolerance.md)
    kill:shard:1@5         kill slice 1's RENDEZVOUS SHARD inside the
                           master when any worker reports step 5: the
                           shard actor is rebuilt from its state
                           partition (rendezvous_shards.py
                           restart_shard) while every other slice's
                           shard keeps serving — the shard-scoped
                           failure-domain drill
    hang:shard:1@5:3       WEDGE slice 1's rendezvous shard for 3 s at
                           step 5: its callers stall at the router
                           boundary; other slices' joins and cuts are
                           provably unaffected (the regression test in
                           tests/test_controlplane.py)
    preempt:worker:1@4:20  rank 1 receives an advance PREEMPTION NOTICE
                           at step 4 with a 20 s grace window: the fault
                           atomically writes the notice file the agent's
                           PreemptionWatcher polls
                           ($DLROVER_TPU_PREEMPTION_NOTICE), driving the
                           whole drain chain — notice RPC, urgent
                           checkpoint fan-out, deadline-bounded
                           emergency save, clean-drain exit, one-round
                           world re-formation — deterministically
                           in-process. Grace defaults to
                           Context.preempt_default_grace_s.
    hang:worker:1@3        rank 1 blocks at step 3 (default 60 s) — with
                           DLROVER_TPU_HANG_WATCHDOG_S under the block
                           length, the step-hang watchdog fires first:
                           stack dump + self-abort + agent restart
    kill:slice:0@5         SIGKILL EVERY rank of ICI slice 0 when it
                           reaches step 5 (multi-slice hierarchical DP:
                           the rank field addresses the SLICE; each
                           member matches on its own
                           $DLROVER_TPU_SLICE_ID and fires at the step,
                           so the fault fans across the slice) — the
                           whole-slice failure-domain drill: survivors
                           keep stepping degraded, the victim slice
                           re-forms alone
    preempt:slice:1@4:20   every rank of slice 1 receives the advance
                           preemption notice at step 4 (20 s grace):
                           the slice drains AS A UNIT — notice RPC,
                           slice-wide drain fan-out, emergency saves,
                           one-round re-formation of the survivors
    resize:-2@10           the 2 HIGHEST-ranked workers leave the world
                           at step 10 with the clean-drain exit (the
                           deterministic scale-DOWN: the master removes
                           them as planned departures, survivors
                           re-plan the parallelism for the smaller
                           world and re-form in one round). The rank
                           field carries the signed delta. Multi-slice
                           jobs must set $DLROVER_TPU_NODE_NUM (fleet
                           rank count): WORLD_SIZE is slice-local there
                           while node ranks are fleet-global.
    resize:+2@10           worker rank 0 atomically writes a scale-UP
                           request ({"delta": 2, ...}) to
                           $DLROVER_TPU_RESIZE_REQUEST at step 10; the
                           LAUNCHER (bench/test harness, operator)
                           consumes it and starts 2 more agents —
                           adding ranks needs a process spawner, which
                           lives outside the worker by construction
    resize:slice:-1@10     slice-unit scale-down: every rank whose
                           slice id is among the $DLROVER_TPU_NUM_SLICES
                           highest leaves with the clean-drain exit at
                           step 10 (requires NUM_SLICES in the env;
                           resize:slice:+k writes the request file with
                           unit="slice")
    offer:slice:+1@10:300  preemptible-market event: the MASTER-side
                           injector hands the local CapacityProvider
                           (brain/fleet_controller.py) an offer of 1
                           spot slice with an expected lifetime of
                           300 s when any worker reports step 10. TTL
                           omitted → the provider's default expected
                           lifetime. The fleet controller then decides
                           whether claiming it beats the join+re-plan
                           cost — the offer alone changes nothing.
    revoke:slice:1@10:20   the spot market takes slice 1 back at step
                           10 with a 20 s grace window. Fires on BOTH
                           sides: every member of slice 1 receives the
                           advance preemption notice (the same file +
                           drain chain as preempt:slice — the PR 5
                           path, unchanged), and the master-side
                           injector tells the CapacityProvider the
                           capacity is gone so the controller prices
                           the revocation instead of diagnosing a
                           surprise. Grace omitted →
                           Context.preempt_default_grace_s.

Each kill/hang/preempt/resize fault fires at most once per process;
slow applies from its step onward. Resize faults additionally record a
JOB-wide consumed marker (with CHAOS_STATE_ENV set) the moment the
step fires: the departing set is decided against the world at fire
time, so a survivor respawned into the post-resize world never
re-evaluates the delta against the smaller world and cascades the
drain. The hook is a no-op (one env read at construction)
when the variable is unset — zero cost on the training path.

One-shot markers (CHAOS_STATE_ENV) are keyed by the fault's INDEX in
the full spec (not just action/role/rank/step), so duplicate faults
fire independently, and are created atomically (O_EXCL) so two racing
incarnations cannot both claim an unfired fault.

The transport-level twin — probabilistic RPC drop/delay/error via
DLROVER_TPU_CHAOS_NET — lives in common/comm.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import List, Optional

from dlrover_tpu.common.log import default_logger as logger

CHAOS_ENV = "DLROVER_TPU_CHAOS"
# directory recording fired one-shot faults: makes kill/hang fire once
# per JOB rather than once per process (a respawned worker re-parses the
# same env; without the marker a kill fault would SIGKILL every
# incarnation and exhaust the restart budget). Unset = per-process only.
CHAOS_STATE_ENV = "DLROVER_TPU_CHAOS_STATE"


@dataclasses.dataclass
class ChaosFault:
    action: str            # "kill" | "hang" | "slow" | "preempt" |
    #                        "resize" | "offer" | "revoke"
    role: str              # node type the fault targets ("worker",
    #                        "master", …); the resize UNIT ("worker" |
    #                        "slice") for resize faults; "slice" for
    #                        the market faults (offer/revoke)
    rank: int              # node rank within the role; the SIGNED
    #                        delta for resize faults; the offered
    #                        slice COUNT for offer; the revoked slice
    #                        id for revoke
    at_step: int           # fire when the target reaches this step
    # hang: block seconds; slow: sleep/step; preempt/revoke: grace
    # window (<= 0 → Context.preempt_default_grace_s); offer: expected
    # lifetime TTL (<= 0 → the provider's default)
    duration: float = 60.0
    fired: bool = False
    # position in the FULL spec (before role/rank filtering): the
    # one-shot marker key, stable across respawns that re-parse the
    # same env — and distinct for duplicate faults
    index: int = 0


def parse_chaos(spec: str) -> List[ChaosFault]:
    """Parse the CHAOS_ENV grammar; raises ValueError on a bad spec (a
    chaos run with a typo'd fault must fail loudly, not run clean)."""
    faults = []
    for index, part in enumerate(
            filter(None, (p.strip() for p in spec.split(";")))):
        try:
            head, at = part.split("@", 1)
            head_fields = head.split(":")
            at_fields = at.split(":")
            if head_fields[0].strip().lower() == "resize":
                # resize:±k@step (ranks) / resize:slice:±k@step
                # (slices): the "rank" field carries the SIGNED delta
                if len(head_fields) == 2:
                    role, delta = "worker", head_fields[1]
                else:
                    role, delta = head_fields[1], head_fields[2]
                delta_n = int(delta)
                if delta_n == 0:
                    raise ValueError("resize delta must be non-zero")
                fault = ChaosFault(
                    action="resize", role=role.strip(),
                    rank=delta_n, at_step=int(at_fields[0]),
                    index=index)
                if fault.role not in ("worker", "slice"):
                    raise ValueError(
                        f"resize unit must be worker or slice, "
                        f"got {fault.role!r}")
                faults.append(fault)
                continue
            action, role, rank = head_fields
            fault = ChaosFault(
                action=action.strip().lower(), role=role.strip(),
                rank=int(rank), at_step=int(at_fields[0]),
                index=index,
            )
            if len(at_fields) > 1:
                fault.duration = float(at_fields[1])
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"bad chaos fault {part!r} (want "
                f"'action:role:rank@step[:duration]' or "
                f"'resize:[slice:]±k@step'): {e}") from e
        if fault.action not in ("kill", "hang", "slow", "preempt",
                                "offer", "revoke"):
            raise ValueError(f"unknown chaos action {fault.action!r}")
        if fault.action in ("preempt", "revoke") and len(at_fields) == 1:
            fault.duration = 0.0   # grace resolves from Context at fire
        if fault.action == "offer":
            # offer:slice:+k@step[:ttl] — the rank field is the offered
            # slice COUNT (the grammar writes it signed, like resize)
            if fault.role != "slice":
                raise ValueError(
                    f"offer targets slices, got role {fault.role!r}")
            if fault.rank <= 0:
                raise ValueError(
                    f"offer count must be positive, got {fault.rank}")
            if len(at_fields) == 1:
                fault.duration = 0.0   # TTL → the provider's default
        elif fault.action == "revoke" and fault.role != "slice":
            raise ValueError(
                f"revoke targets slices, got role {fault.role!r}")
        elif fault.rank < 0:
            raise ValueError(
                f"chaos fault {part!r} has negative rank {fault.rank} "
                f"(no node can match it)")
        faults.append(fault)
    return faults


class ChaosInjector:
    """Per-process injector; construct once, call maybe_inject per step."""

    def __init__(self, role: str = "worker",
                 rank: Optional[int] = None,
                 spec: Optional[str] = None,
                 slice_id: Optional[int] = None):
        from dlrover_tpu.common.constants import NodeEnv

        spec = spec if spec is not None else os.environ.get(CHAOS_ENV, "")
        if rank is None:
            rank = int(os.environ.get(NodeEnv.NODE_RANK, "0"))
        if slice_id is None:
            slice_id = int(os.environ.get(NodeEnv.SLICE_ID, "-1"))
        self._role = role
        self._rank = rank
        self._slice = slice_id
        self._state_dir = os.environ.get(CHAOS_STATE_ENV, "")
        # control-plane shard faults (kill:shard:S / hang:shard:S):
        # handled by the MASTER-side injector through these hooks
        # (JobMaster wires them to the sharded rendezvous router)
        self.shard_kill_fn = None
        self.shard_wedge_fn = None
        # preemptible-market faults (offer/revoke): handled by the
        # MASTER-side injector through these hooks (JobMaster wires
        # them to the fleet controller's local CapacityProvider);
        # offer_fn(count, ttl_s, step), revoke_fn(slice_id, grace_s,
        # step)
        self.offer_fn = None
        self.revoke_fn = None
        # a "slice"-role fault addresses the SLICE in its rank field:
        # every member of that slice arms it, so kill/preempt/revoke
        # fan across the whole failure domain. Resize faults arm on
        # EVERY worker — whether this rank is part of the delta is
        # decided at fire time against the live world/slice count.
        # "shard"-role faults arm on the MASTER (the shard lives in its
        # process), and so do the market faults (offer on the master
        # ONLY; revoke on the master AND on the revoked slice's
        # members, which reuse the preemption-notice path verbatim).
        self.faults = [
            f for f in parse_chaos(spec)
            if (f.action == "resize" and role == "worker")
            or (f.action == "offer" and role == "master")
            or (f.action == "revoke"
                and (role == "master"
                     or (role == "worker" and slice_id >= 0
                         and f.rank == slice_id)))
            or (f.action not in ("resize", "offer", "revoke")
                and ((f.role == role and f.rank == rank)
                     or (f.role == "slice" and role == "worker"
                         and slice_id >= 0 and f.rank == slice_id)
                     or (f.role == "shard" and role == "master")))
        ] if spec else []
        for fault in self.faults:
            if self._already_fired(fault):
                fault.fired = True
        if self.faults:
            logger.warning("chaos injector ARMED for %s-%d: %s",
                           role, rank, self.faults)

    def _marker(self, fault: ChaosFault) -> str:
        # keyed by spec index: two faults that agree on
        # action/role/rank/step still get their own markers. A
        # slice-role or resize fault additionally keys on THIS node's
        # rank — every affected member must fire its own copy (one
        # shared marker would let the first member claim the whole
        # unit's fault and leave the rest alive). The master's copy of
        # a market fault gets its own suffix: worker rank 0 may share
        # the state dir, and its _n0 marker must not consume the
        # master-side provider notification (or vice versa).
        if self._role == "master" and fault.action in ("offer", "revoke"):
            per_node = "_market"
        elif fault.role == "slice" or fault.action == "resize":
            per_node = f"_n{self._rank}"
        else:
            per_node = ""
        return os.path.join(
            self._state_dir,
            f"chaos_{fault.index}_{fault.action}_{fault.role}"
            f"_{fault.rank}_{fault.at_step}{per_node}")

    def _job_marker(self, fault: ChaosFault) -> str:
        """Resize faults additionally keep a JOB-wide marker recording
        the world (or slice count) at fire time: the departing set is
        decided against THAT world — a survivor respawned into the
        post-resize world must not re-evaluate the delta against the
        new (smaller) world and cascade the drain, while a LEAVING
        rank respawned before it reached ``at_step`` must still fire
        (suppressing it would remove fewer than k ranks)."""
        return self._marker(fault).replace(f"_n{self._rank}", "_job")

    def _job_fire_world(self, fault: ChaosFault) -> Optional[int]:
        """The world size recorded when the resize fault first fired
        anywhere in the job; None = not fired yet (or no state dir)."""
        if not self._state_dir:
            return None
        try:
            with open(self._job_marker(fault)) as f:
                payload = json.loads(f.read() or "{}")
            return int(payload.get("world", 0)) or 0
        except (OSError, ValueError):
            return None

    def _record_job_fired(self, fault: ChaosFault, world: int) -> None:
        """First claimer records the fire-time world (O_EXCL: exactly
        one writer; later rank/incarnations read it back)."""
        if not self._state_dir:
            return
        try:
            os.makedirs(self._state_dir, exist_ok=True)
            fd = os.open(self._job_marker(fault),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return
        with os.fdopen(fd, "w") as f:
            json.dump({"world": int(world), "pid": os.getpid()}, f)

    def _resize_leaving(self, fault: ChaosFault, world: int) -> bool:
        """Is THIS process in the departing set of a scale-down fault
        judged against ``world`` (ranks or slices)?"""
        member = self._slice if fault.role == "slice" else self._rank
        return member >= world + fault.rank

    def _already_fired(self, fault: ChaosFault) -> bool:
        if not self._state_dir:
            return False
        if os.path.exists(self._marker(fault)):
            return True
        if fault.action != "resize":
            return False
        fired_world = self._job_fire_world(fault)
        if fired_world is None:
            return False
        if fault.rank > 0 or not fired_world:
            # scale-up (single writer) — or a marker predating the
            # world payload: conservatively consumed
            return True
        # scale-down: consumed for survivors of the FIRE-TIME world;
        # a leaver that respawned before reaching at_step must still
        # fire (its own per-node marker records its actual exit)
        return not self._resize_leaving(fault, fired_world)

    def _record_fired(self, fault: ChaosFault) -> bool:
        """Claim the one-shot marker; returns whether THIS process won.
        O_CREAT|O_EXCL is the atomicity: a racing incarnation loses the
        create and must not fire the fault a second time."""
        fault.fired = True
        if not self._state_dir:
            return True
        os.makedirs(self._state_dir, exist_ok=True)
        try:
            fd = os.open(self._marker(fault),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(str(os.getpid()))
        return True

    def maybe_inject(self, step: int) -> None:
        for fault in self.faults:
            if fault.fired or step < fault.at_step:
                continue
            if fault.role == "shard":
                self._inject_shard_fault(fault, step)
            elif (fault.action in ("offer", "revoke")
                    and self._role == "master"):
                self._inject_market(fault, step)
            elif fault.action == "revoke":
                # worker side: the revoked slice's members receive the
                # standard advance preemption notice — the established
                # drain chain, unchanged
                if not self._record_fired(fault):
                    continue
                self._write_preemption_notice(fault, step)
            elif fault.action == "kill":
                # record BEFORE dying, or the respawned incarnation
                # replays the fault forever
                if not self._record_fired(fault):
                    continue
                logger.warning("chaos: SIGKILL self (%s-%d) at step %d",
                               self._role, self._rank, step)
                os.kill(os.getpid(), signal.SIGKILL)
            elif fault.action == "hang":
                logger.warning("chaos: hanging %s-%d for %.1fs at step %d",
                               self._role, self._rank, fault.duration,
                               step)
                time.sleep(fault.duration)
                # record AFTER the sleep: a process killed and respawned
                # mid-hang must replay the hang, not skip it
                self._record_fired(fault)
            elif fault.action == "preempt":
                # record BEFORE writing the notice: the drain respawns
                # nothing on this node, but a later incarnation (e.g.
                # the drain was cancelled operator-side) must not
                # re-preempt itself forever
                if not self._record_fired(fault):
                    continue
                self._write_preemption_notice(fault, step)
            elif fault.action == "resize":
                self._inject_resize(fault, step)
            elif fault.action == "slow":
                # applies every step from at_step on (a real straggler)
                time.sleep(fault.duration)

    def _inject_shard_fault(self, fault: ChaosFault, step: int) -> None:
        """Shard-scoped control-plane faults, executed through the hooks
        the master wired in (no-op with a warning when the training
        manager is not sharded)."""
        if fault.action == "kill":
            if not self._record_fired(fault):
                return
            if self.shard_kill_fn is None:
                logger.warning(
                    "chaos kill:shard:%d armed but no sharded "
                    "rendezvous manager to kill (rdzv_sharded off?)",
                    fault.rank)
                return
            logger.warning("chaos: killing rendezvous shard %d at "
                           "step %d", fault.rank, step)
            self.shard_kill_fn(fault.rank)
        elif fault.action == "hang":
            if not self._record_fired(fault):
                return
            if self.shard_wedge_fn is None:
                logger.warning(
                    "chaos hang:shard:%d armed but no sharded "
                    "rendezvous manager to wedge (rdzv_sharded off?)",
                    fault.rank)
                return
            logger.warning("chaos: wedging rendezvous shard %d for "
                           "%.1fs at step %d", fault.rank,
                           fault.duration, step)
            self.shard_wedge_fn(fault.rank, fault.duration)
        else:
            logger.warning("chaos: unsupported shard fault %s ignored",
                           fault.action)
            fault.fired = True

    def _inject_market(self, fault: ChaosFault, step: int) -> None:
        """Master-side preemptible-market events, delivered to the
        fleet controller's local CapacityProvider through the wired
        hooks (no-op with a warning when no controller is running)."""
        if not self._record_fired(fault):
            return
        if fault.action == "offer":
            if self.offer_fn is None:
                logger.warning(
                    "chaos offer:slice:+%d armed but no capacity "
                    "provider wired (fleet_controller_enabled off?)",
                    fault.rank)
                return
            logger.warning(
                "chaos: market offers %d slice(s) at step %d "
                "(ttl %.1fs)", fault.rank, step, fault.duration)
            self.offer_fn(fault.rank, fault.duration, step)
            return
        from dlrover_tpu.common.config import Context

        grace = (fault.duration if fault.duration > 0
                 else Context.singleton().preempt_default_grace_s)
        if self.revoke_fn is None:
            logger.warning(
                "chaos revoke:slice:%d armed but no capacity provider "
                "wired (fleet_controller_enabled off?)", fault.rank)
            return
        logger.warning("chaos: market revokes slice %d at step %d "
                       "(grace %.1fs)", fault.rank, step, grace)
        self.revoke_fn(fault.rank, grace, step)

    def _inject_resize(self, fault: ChaosFault, step: int) -> None:
        """Deterministic mid-run resize. Scale-DOWN (delta < 0): this
        process leaves with the clean-drain exit when its rank (or
        slice) is among the |delta| highest — the agent concludes a
        planned departure, the master removes the rank immediately and
        survivors re-plan + re-form in ONE round. Scale-UP (delta > 0):
        rank 0 atomically writes the resize-request file the LAUNCHER
        polls (spawning processes is the launcher's power, not the
        worker's)."""
        from dlrover_tpu.common.constants import NodeEnv, WorkerExit

        delta = fault.rank
        if delta < 0:
            # the departing set is judged against the world at FIRST
            # fire: the job marker's recorded size wins over the env —
            # a respawn into the already-shrunken world must neither
            # cascade (survivor re-draining) nor under-deliver (a
            # leaver that had not reached at_step yet)
            if fault.role == "slice":
                world_env = NodeEnv.NUM_SLICES
            elif self._slice >= 0:
                # multi-slice job: WORLD_SIZE is the SLICE-LOCAL comm
                # world (per-slice worlds, PR 8) while node ranks are
                # fleet-global — a worker-unit delta needs the fleet
                # rank count or the wrong ranks drain
                world_env = NodeEnv.NODE_NUM
            else:
                world_env = NodeEnv.WORLD_SIZE
            world = (self._job_fire_world(fault)
                     or int(os.environ.get(world_env, "0")))
            if world <= 0:
                logger.warning(
                    "chaos resize:%s%d needs %s in the env; skipping",
                    "slice:" if fault.role == "slice" else "", delta,
                    world_env)
                fault.fired = True
                return
            self._record_job_fired(fault, world)
            leaving = self._resize_leaving(fault, world)
            fault.fired = True
            if not leaving:
                return
            if not self._record_fired(fault):
                return
            logger.warning(
                "chaos: resize %+d at step %d — %s-%d leaves with the "
                "clean-drain exit (survivors re-plan the smaller "
                "world)", delta, step, self._role, self._rank)
            raise SystemExit(WorkerExit.DRAIN)
        # scale-up: one writer (rank 0) hands the request to the
        # launcher; everyone else just marks the fault consumed
        fault.fired = True
        if self._rank != 0:
            return
        if not self._record_fired(fault):
            return
        path = os.environ.get(NodeEnv.RESIZE_REQUEST_FILE, "")
        logger.warning(
            "chaos: resize %+d (%ss) requested at step %d -> %s",
            delta, fault.role, step, path or "<no request file>")
        if not path:
            return
        payload = {"delta": delta, "unit": fault.role, "step": step,
                   "ts": time.time()}
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    def _write_preemption_notice(self, fault: ChaosFault,
                                 step: int) -> None:
        """Simulate the platform's advance notice: atomically write the
        JSON notice file the agent's PreemptionWatcher polls."""
        import json

        from dlrover_tpu.common.config import Context
        from dlrover_tpu.common.constants import NodeEnv

        path = os.environ.get(NodeEnv.PREEMPTION_NOTICE_FILE, "")
        grace = (fault.duration if fault.duration > 0
                 else Context.singleton().preempt_default_grace_s)
        logger.warning(
            "chaos: preemption notice for %s-%d at step %d "
            "(grace %.1fs) -> %s", self._role, self._rank, step,
            grace, path or "<no notice file configured>")
        if not path:
            return
        payload = {"deadline": time.time() + grace,
                   "grace_s": grace,
                   "reason": f"chaos {fault.action}@{fault.at_step}"}
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
