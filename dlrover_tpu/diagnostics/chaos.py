"""Scriptable fault injection for chaos testing.

Capability parity: the reference ships chaosblade-driven chaos jobs
(/root/reference/examples/pytorch/mnist/start_chaos.sh +
chaos_test_job.yaml — kill/cpu-load a chosen pod while the job runs) to
demonstrate recovery. TPU re-design: no external agent — a worker-side
hook the train loop polls each step, scripted through one env var, so a
chaos run is just a normal job launch with `DLROVER_TPU_CHAOS` set on
(or forwarded to) the chosen node.

Spec grammar (semicolon-separated faults):

    DLROVER_TPU_CHAOS="action:role:rank@step[:duration]"

    kill:worker:0@5        SIGKILL worker rank 0 when it reaches step 5
    hang:worker:1@3:120    rank 1 blocks 120 s at step 3 (hang detector
                           / straggler territory)
    slow:worker:2@4:0.5    rank 2 sleeps 0.5 s EVERY step from step 4 on
                           (a straggler the network-check/speed paths
                           should flag)
    kill:master:0@5        SIGKILL the job MASTER when any worker reports
                           step 5 (the servicer feeds worker
                           GlobalStepReports to a master-side injector) —
                           exercises crash-consistent state recovery +
                           agent reconnection (docs/fault_tolerance.md)
    preempt:worker:1@4:20  rank 1 receives an advance PREEMPTION NOTICE
                           at step 4 with a 20 s grace window: the fault
                           atomically writes the notice file the agent's
                           PreemptionWatcher polls
                           ($DLROVER_TPU_PREEMPTION_NOTICE), driving the
                           whole drain chain — notice RPC, urgent
                           checkpoint fan-out, deadline-bounded
                           emergency save, clean-drain exit, one-round
                           world re-formation — deterministically
                           in-process. Grace defaults to
                           Context.preempt_default_grace_s.
    hang:worker:1@3        rank 1 blocks at step 3 (default 60 s) — with
                           DLROVER_TPU_HANG_WATCHDOG_S under the block
                           length, the step-hang watchdog fires first:
                           stack dump + self-abort + agent restart
    kill:slice:0@5         SIGKILL EVERY rank of ICI slice 0 when it
                           reaches step 5 (multi-slice hierarchical DP:
                           the rank field addresses the SLICE; each
                           member matches on its own
                           $DLROVER_TPU_SLICE_ID and fires at the step,
                           so the fault fans across the slice) — the
                           whole-slice failure-domain drill: survivors
                           keep stepping degraded, the victim slice
                           re-forms alone
    preempt:slice:1@4:20   every rank of slice 1 receives the advance
                           preemption notice at step 4 (20 s grace):
                           the slice drains AS A UNIT — notice RPC,
                           slice-wide drain fan-out, emergency saves,
                           one-round re-formation of the survivors

Each kill/hang/preempt fault fires at most once per process; slow
applies from its step onward. The hook is a no-op (one env read at construction)
when the variable is unset — zero cost on the training path.

One-shot markers (CHAOS_STATE_ENV) are keyed by the fault's INDEX in
the full spec (not just action/role/rank/step), so duplicate faults
fire independently, and are created atomically (O_EXCL) so two racing
incarnations cannot both claim an unfired fault.

The transport-level twin — probabilistic RPC drop/delay/error via
DLROVER_TPU_CHAOS_NET — lives in common/comm.py.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import List, Optional

from dlrover_tpu.common.log import default_logger as logger

CHAOS_ENV = "DLROVER_TPU_CHAOS"
# directory recording fired one-shot faults: makes kill/hang fire once
# per JOB rather than once per process (a respawned worker re-parses the
# same env; without the marker a kill fault would SIGKILL every
# incarnation and exhaust the restart budget). Unset = per-process only.
CHAOS_STATE_ENV = "DLROVER_TPU_CHAOS_STATE"


@dataclasses.dataclass
class ChaosFault:
    action: str            # "kill" | "hang" | "slow" | "preempt"
    role: str              # node type the fault targets ("worker",
    #                        "master", …)
    rank: int              # node rank within the role
    at_step: int           # fire when the target reaches this step
    # hang: block seconds; slow: sleep/step; preempt: grace window
    # (<= 0 → Context.preempt_default_grace_s)
    duration: float = 60.0
    fired: bool = False
    # position in the FULL spec (before role/rank filtering): the
    # one-shot marker key, stable across respawns that re-parse the
    # same env — and distinct for duplicate faults
    index: int = 0


def parse_chaos(spec: str) -> List[ChaosFault]:
    """Parse the CHAOS_ENV grammar; raises ValueError on a bad spec (a
    chaos run with a typo'd fault must fail loudly, not run clean)."""
    faults = []
    for index, part in enumerate(
            filter(None, (p.strip() for p in spec.split(";")))):
        try:
            head, at = part.split("@", 1)
            action, role, rank = head.split(":")
            at_fields = at.split(":")
            fault = ChaosFault(
                action=action.strip().lower(), role=role.strip(),
                rank=int(rank), at_step=int(at_fields[0]),
                index=index,
            )
            if len(at_fields) > 1:
                fault.duration = float(at_fields[1])
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"bad chaos fault {part!r} (want "
                f"'action:role:rank@step[:duration]'): {e}") from e
        if fault.action not in ("kill", "hang", "slow", "preempt"):
            raise ValueError(f"unknown chaos action {fault.action!r}")
        if fault.action == "preempt" and len(at_fields) == 1:
            fault.duration = 0.0   # grace resolves from Context at fire
        if fault.rank < 0:
            raise ValueError(
                f"chaos fault {part!r} has negative rank {fault.rank} "
                f"(no node can match it)")
        faults.append(fault)
    return faults


class ChaosInjector:
    """Per-process injector; construct once, call maybe_inject per step."""

    def __init__(self, role: str = "worker",
                 rank: Optional[int] = None,
                 spec: Optional[str] = None,
                 slice_id: Optional[int] = None):
        from dlrover_tpu.common.constants import NodeEnv

        spec = spec if spec is not None else os.environ.get(CHAOS_ENV, "")
        if rank is None:
            rank = int(os.environ.get(NodeEnv.NODE_RANK, "0"))
        if slice_id is None:
            slice_id = int(os.environ.get(NodeEnv.SLICE_ID, "-1"))
        self._role = role
        self._rank = rank
        self._slice = slice_id
        self._state_dir = os.environ.get(CHAOS_STATE_ENV, "")
        # a "slice"-role fault addresses the SLICE in its rank field:
        # every member of that slice arms it, so kill/preempt fan
        # across the whole failure domain
        self.faults = [
            f for f in parse_chaos(spec)
            if (f.role == role and f.rank == rank)
            or (f.role == "slice" and role == "worker"
                and slice_id >= 0 and f.rank == slice_id)
        ] if spec else []
        for fault in self.faults:
            if self._already_fired(fault):
                fault.fired = True
        if self.faults:
            logger.warning("chaos injector ARMED for %s-%d: %s",
                           role, rank, self.faults)

    def _marker(self, fault: ChaosFault) -> str:
        # keyed by spec index: two faults that agree on
        # action/role/rank/step still get their own markers. A
        # slice-role fault additionally keys on THIS node's rank —
        # every member of the slice must fire its own copy (one shared
        # marker would let the first member claim the whole slice's
        # fault and leave the rest alive).
        per_node = f"_n{self._rank}" if fault.role == "slice" else ""
        return os.path.join(
            self._state_dir,
            f"chaos_{fault.index}_{fault.action}_{fault.role}"
            f"_{fault.rank}_{fault.at_step}{per_node}")

    def _already_fired(self, fault: ChaosFault) -> bool:
        return bool(self._state_dir) and os.path.exists(
            self._marker(fault))

    def _record_fired(self, fault: ChaosFault) -> bool:
        """Claim the one-shot marker; returns whether THIS process won.
        O_CREAT|O_EXCL is the atomicity: a racing incarnation loses the
        create and must not fire the fault a second time."""
        fault.fired = True
        if not self._state_dir:
            return True
        os.makedirs(self._state_dir, exist_ok=True)
        try:
            fd = os.open(self._marker(fault),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(str(os.getpid()))
        return True

    def maybe_inject(self, step: int) -> None:
        for fault in self.faults:
            if fault.fired or step < fault.at_step:
                continue
            if fault.action == "kill":
                # record BEFORE dying, or the respawned incarnation
                # replays the fault forever
                if not self._record_fired(fault):
                    continue
                logger.warning("chaos: SIGKILL self (%s-%d) at step %d",
                               self._role, self._rank, step)
                os.kill(os.getpid(), signal.SIGKILL)
            elif fault.action == "hang":
                logger.warning("chaos: hanging %s-%d for %.1fs at step %d",
                               self._role, self._rank, fault.duration,
                               step)
                time.sleep(fault.duration)
                # record AFTER the sleep: a process killed and respawned
                # mid-hang must replay the hang, not skip it
                self._record_fired(fault)
            elif fault.action == "preempt":
                # record BEFORE writing the notice: the drain respawns
                # nothing on this node, but a later incarnation (e.g.
                # the drain was cancelled operator-side) must not
                # re-preempt itself forever
                if not self._record_fired(fault):
                    continue
                self._write_preemption_notice(fault, step)
            elif fault.action == "slow":
                # applies every step from at_step on (a real straggler)
                time.sleep(fault.duration)

    def _write_preemption_notice(self, fault: ChaosFault,
                                 step: int) -> None:
        """Simulate the platform's advance notice: atomically write the
        JSON notice file the agent's PreemptionWatcher polls."""
        import json

        from dlrover_tpu.common.config import Context
        from dlrover_tpu.common.constants import NodeEnv

        path = os.environ.get(NodeEnv.PREEMPTION_NOTICE_FILE, "")
        grace = (fault.duration if fault.duration > 0
                 else Context.singleton().preempt_default_grace_s)
        logger.warning(
            "chaos: preemption notice for %s-%d at step %d "
            "(grace %.1fs) -> %s", self._role, self._rank, step,
            grace, path or "<no notice file configured>")
        if not path:
            return
        payload = {"deadline": time.time() + grace,
                   "grace_s": grace,
                   "reason": f"chaos preempt@{fault.at_step}"}
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
