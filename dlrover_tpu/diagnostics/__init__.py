"""Health diagnostics: ICI/DCN probes, fault isolation, straggler detection."""
