"""LLaMA-family model in flax.linen, TPU-first.

Capability parity: the reference accelerates LLaMA-style models via atorch
(LlamaAttentionFA atorch/modules/transformer/layers.py:1279; Megatron-style
col/row-parallel projections modules/distributed_modules/layers.py:239-670).
TPU re-design: one set of plain matmul modules whose parameters carry
*logical axis names* (`embed`, `heads`, `kv`, `head_dim`, `mlp`, `vocab`);
tensor/fsdp/sequence parallelism become sharding rules applied at jit time
(dlrover_tpu/parallel/sharding.py) instead of distinct module classes —
XLA inserts the collectives the Megatron classes perform by hand.

Attention runs through the Pallas flash kernel (dlrover_tpu/ops) or a plain
XLA path (`attn_impl="reference"`), selected per config.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dlrover_tpu.ops.flash_attention import (
    mesh_flash_attention,
    reference_attention,
)
from dlrover_tpu.ops.norms import fused_rms_norm, reference_rms_norm
from dlrover_tpu.ops.remat import resolve_remat_policy


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16        # activation/compute dtype
    param_dtype: Any = jnp.float32   # master parameter dtype
    # "flash" (Pallas kernel) | "reference" (XLA) | "ring" (sequence-
    # parallel ppermute KV rotation) | "ulysses" (sequence-parallel
    # all-to-all head dispatch). ring/ulysses shard the sequence dim over
    # the mesh's `sequence` axis (parallel/ring_attention.py) and need the
    # ambient mesh build_trainer provides at trace time.
    attn_impl: str = "flash"
    # "onehot": iota/one-hot matmul lookup — SPMD-partitions as a plain
    # matmul, so the embedding-table gradient never hits the scatter path
    # that forces XLA into involuntary full rematerialization on a
    # (data, fsdp, tensor) mesh. "gather" is cheaper on a single chip.
    embed_impl: str = "onehot"
    norm_impl: str = "fused"         # "fused" (Pallas) | "reference" (XLA)
    remat: bool = False              # rematerialize each block
    # "full"/"nothing_saveable" | "dots"/"dots_saveable" | "dots_with_no_batch_dims"
    remat_policy: str = "nothing_saveable"
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    # ---- stock sizes -----------------------------------------------------
    @classmethod
    def llama_1b(cls, **kw) -> "LlamaConfig":
        return cls(hidden_size=2048, intermediate_size=5504, num_layers=22,
                   num_heads=16, num_kv_heads=16, **kw)

    @classmethod
    def llama_7b(cls, **kw) -> "LlamaConfig":
        return cls(hidden_size=4096, intermediate_size=11008,
                   num_layers=32, num_heads=32, num_kv_heads=32, **kw)

    @classmethod
    def llama_wide_1b(cls, **kw) -> "LlamaConfig":
        """Gemma-style wide-MLP variant (i/h = 4 instead of Llama's 2.7),
        tuned for single-chip MFU: the MLP matmul is the near-peak part
        of the step (98% of peak measured on v5e at these shapes), so at
        a fixed HBM budget, trading attention/norm layers for MLP width
        raises utilization — 0.66 vs 0.63 MFU against llama_1b."""
        return cls(hidden_size=2048, intermediate_size=8192,
                   num_layers=20, num_heads=16, num_kv_heads=16, **kw)

    @classmethod
    def llama_410m(cls, **kw) -> "LlamaConfig":
        return cls(hidden_size=1024, intermediate_size=2816, num_layers=24,
                   num_heads=8, num_kv_heads=8, **kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        return cls(hidden_size=64, intermediate_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, rms_norm_eps=1e-5, **kw)

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6·params +
        attention term 12·L·H·T·d at seq T) — used for MFU accounting."""
        params = self.param_count()
        return 6.0 * params

    def param_count(self) -> int:
        h, i, v, L = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_layers)
        kv = self.num_kv_heads * self.head_dim
        per_layer = (
            h * h + 2 * h * kv + h * h      # q, k, v, o projections
            + 3 * h * i                      # gate, up, down
            + 2 * h                          # 2 rmsnorm scales
        )
        emb = v * h * (1 if self.tie_embeddings else 2)
        return L * per_layer + emb + h


def _logical(init, *axes):
    return nn.with_logical_partitioning(init, axes)


def embed_lookup(embed: jax.Array, tokens: jax.Array, cfg: Any) -> jax.Array:
    """Token embedding lookup; see LlamaConfig.embed_impl. cfg only needs
    embed_impl / vocab_size / dtype (GPTConfig works too)."""
    if cfg.embed_impl == "onehot":
        onehot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
        return jnp.dot(onehot, embed.astype(cfg.dtype))
    return embed.astype(cfg.dtype)[tokens]


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    impl: str = "fused"

    @nn.compact
    def __call__(self, x):
        weight = self.param(
            "weight", _logical(nn.initializers.ones, "norm"), (x.shape[-1],)
        )
        # The fused kernel only on real TPU: off-TPU it would run in
        # Pallas interpret mode — slow, and its interpreter loop breaks
        # the vma typing inside partial-auto shard_map (pipeline stages)
        if self.impl == "fused" and jax.default_backend() == "tpu":
            return fused_rms_norm(x, weight.astype(jnp.float32),
                                  self.eps).astype(self.dtype)
        return reference_rms_norm(x, weight.astype(jnp.float32),
                                  self.eps).astype(self.dtype)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float) -> jax.Array:
    """Rotary embedding on (..., seq, num_heads, head_dim)."""
    head_dim = x.shape[-1]
    freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., :, None].astype(jnp.float32) * freq  # (b, s, d/2)
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def _sequence_parallel_mesh():
    """The ambient mesh when it has an active sequence axis, else None
    (→ the caller falls back to plain attention)."""
    from dlrover_tpu.common.constants import MeshAxis
    from dlrover_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None or mesh.shape.get(MeshAxis.SEQUENCE, 1) == 1:
        return None
    return mesh


def _sequence_parallel_attention(impl, mesh, q, k, v, causal: bool = True):
    """Dispatch to ring/Ulysses attention on (b, seq, heads, dim) arrays;
    k/v carry the (smaller) GQA head count — the kernels replicate heads
    after sharding so only KV-sized bytes ride the ICI.

    Capability parity: atorch DistributedSelfAttention wired into the real
    transformer blocks (distributed_attention.py:21-115, commu_utils.py:6,47)
    — here the model reaches the sequence-parallel kernels directly via
    `attn_impl`, with the mesh taken from the ambient context that
    build_trainer establishes at trace time."""
    from dlrover_tpu.common.constants import MeshAxis
    from dlrover_tpu.parallel.ring_attention import (
        ring_attention,
        ulysses_attention,
    )

    head_axis = (MeshAxis.TENSOR
                 if mesh.shape.get(MeshAxis.TENSOR, 1) > 1 else None)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, mesh, causal=causal,
                                 head_axis=head_axis)
    return ring_attention(q, k, v, mesh, causal=causal,
                          head_axis=head_axis)


def dispatch_attention(impl: str, q, k, v, causal: bool = True):
    """Shared attention dispatch for the model families (GPT, BERT, …):
    (b, seq, heads, dim) in and out, impl = flash | reference | ring |
    ulysses. The SP impls need an ambient mesh with an active `sequence`
    axis (build_trainer establishes it at trace time); off-mesh they fall
    back to the plain path so unit runs stay valid."""
    if impl in ("ring", "ulysses"):
        sp_mesh = _sequence_parallel_mesh()
        if sp_mesh is not None:
            return _sequence_parallel_attention(impl, sp_mesh, q, k, v,
                                                causal)
        impl = "reference"
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if impl == "flash":
        out = mesh_flash_attention(qt, kt, vt, causal)
    else:
        out = reference_attention(qt, kt, vt, causal)
    return out.transpose(0, 2, 1, 3)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        batch, seq, _ = x.shape
        dense = functools_partial_dense(cfg)
        q = dense("q_proj", (cfg.hidden_size,
                             cfg.num_heads * cfg.head_dim),
                  ("embed", "heads"))(x)
        k = dense("k_proj", (cfg.hidden_size,
                             cfg.num_kv_heads * cfg.head_dim),
                  ("embed", "kv"))(x)
        v = dense("v_proj", (cfg.hidden_size,
                             cfg.num_kv_heads * cfg.head_dim),
                  ("embed", "kv"))(x)
        q = q.reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        k = k.reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        impl = cfg.attn_impl
        sp_mesh = None
        if impl in ("ring", "ulysses"):
            sp_mesh = _sequence_parallel_mesh()
            if sp_mesh is None:
                # Off-mesh (unit runs) or no sequence axis: fall back to
                # the plain path below rather than a degenerate shard_map.
                impl = "reference"
        if sp_mesh is not None:
            out = _sequence_parallel_attention(impl, sp_mesh, q, k, v)
            out = out.reshape(batch, seq, -1)
        else:
            # (b, heads, seq, dim) layout for the kernel
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            if impl == "flash":
                out = mesh_flash_attention(q, k, v, True)
            else:
                out = reference_attention(q, k, v, True)
            out = out.transpose(0, 2, 1, 3).reshape(batch, seq, -1)
        return dense("o_proj",
                     (cfg.num_heads * cfg.head_dim, cfg.hidden_size),
                     ("heads", "embed"))(out)


def functools_partial_dense(cfg: LlamaConfig):
    """A kernel-only linear with named logical axes."""

    def make(name, shape, axes):
        class _Dense(nn.Module):
            @nn.compact
            def __call__(self, x):
                kernel = self.param(
                    "kernel",
                    _logical(nn.initializers.normal(0.02), *axes),
                    shape, cfg.param_dtype,
                )
                return jnp.dot(x, kernel.astype(cfg.dtype))

        return _Dense(name=name)

    return make


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = functools_partial_dense(cfg)
        gate = dense("gate_proj", (cfg.hidden_size, cfg.intermediate_size),
                     ("embed", "mlp"))(x)
        up = dense("up_proj", (cfg.hidden_size, cfg.intermediate_size),
                   ("embed", "mlp"))(x)
        return dense("down_proj", (cfg.intermediate_size, cfg.hidden_size),
                     ("mlp", "embed"))(nn.silu(gate) * up)


ACT_AXES = ("act_batch", "act_seq", "act_embed")


class DecoderBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        # pin the activation layout so SPMD never round-trips the
        # residual stream between layouts (constraint is a no-op off-mesh)
        x = nn.with_logical_constraint(x, ACT_AXES)
        x = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="attn_norm")(x),
            positions,
        )
        x = nn.with_logical_constraint(x, ACT_AXES)
        x = x + MLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="mlp_norm")(x)
        )
        return nn.with_logical_constraint(x, ACT_AXES)


class Llama(nn.Module):
    """Decoder-only LM. `__call__(tokens) -> logits`."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg = self.config
        embed = self.param(
            "embed",
            _logical(nn.initializers.normal(0.02), "vocab", "embed"),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype,
        )
        x = embed_lookup(embed, tokens, cfg)
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[-1]), tokens.shape)
        block_cls = DecoderBlock
        if cfg.remat:
            block_cls = nn.remat(
                DecoderBlock, static_argnums=(),
                policy=resolve_remat_policy(cfg.remat_policy),
            )
        for layer in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{layer}")(x, positions)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = jnp.dot(x, embed.astype(cfg.dtype).T)
        else:
            head = self.param(
                "lm_head",
                _logical(nn.initializers.normal(0.02), "embed", "vocab"),
                (cfg.hidden_size, cfg.vocab_size), cfg.param_dtype,
            )
            logits = jnp.dot(x, head.astype(cfg.dtype))
        return logits.astype(jnp.float32)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross entropy; logits (b, s, v), targets (b, s)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)
