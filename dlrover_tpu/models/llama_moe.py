"""Mixtral-style MoE Llama: sparse expert MLPs in the Llama skeleton.

Capability parity: the reference's MoE model path (atorch modules/moe
MOELayer injected into transformer blocks via moe/inject.py) — here a
first-class model family: Llama attention + RMSNorm with each block's MLP
replaced by the expert-parallel MoELayer (dlrover_tpu/parallel/moe.py).
Router aux losses are sown into the 'losses' collection;
`moe_cross_entropy_loss` folds them into the objective.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import (
    Attention,
    LlamaConfig,
    RMSNorm,
    _logical,
    cross_entropy_loss,
    embed_lookup,
)
from dlrover_tpu.ops.remat import resolve_remat_policy
from dlrover_tpu.parallel.moe import MoEConfig, MoELayer, moe_aux_loss


@dataclasses.dataclass(frozen=True)
class LlamaMoEConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    jitter_noise: float = 0.0
    aux_loss_weight: float = 0.01

    def moe_config(self) -> MoEConfig:
        return MoEConfig(
            num_experts=self.num_experts,
            top_k=self.top_k,
            hidden_size=self.hidden_size,
            expert_intermediate=self.intermediate_size,
            capacity_factor=self.capacity_factor,
            jitter_noise=self.jitter_noise,
            aux_loss_weight=self.aux_loss_weight,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )

    @classmethod
    def mixtral_tiny(cls, **kw) -> "LlamaMoEConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        return cls(hidden_size=64, intermediate_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, num_experts=4, top_k=2,
                   **kw)

    def param_count(self) -> int:
        dense = super().param_count()
        # each layer's single MLP becomes num_experts experts + a router
        per_layer_mlp = 3 * self.hidden_size * self.intermediate_size
        moe_mlp = (2 * self.hidden_size * self.intermediate_size
                   * self.num_experts
                   + self.hidden_size * self.num_experts)
        return dense + self.num_layers * (moe_mlp - per_layer_mlp)

    def active_param_count(self) -> int:
        """Params touched per token (the MoE efficiency headline)."""
        dense = super().param_count()
        per_layer_mlp = 3 * self.hidden_size * self.intermediate_size
        active_mlp = (2 * self.hidden_size * self.intermediate_size
                      * self.top_k
                      + self.hidden_size * self.num_experts)
        return dense + self.num_layers * (active_mlp - per_layer_mlp)


class MoEDecoderBlock(nn.Module):
    config: LlamaMoEConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        x = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="attn_norm")(x),
            positions,
        )
        x = x + MoELayer(cfg.moe_config(),
                         deterministic=self.deterministic, name="moe")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="moe_norm")(x)
        )
        return x


class LlamaMoE(nn.Module):
    """Decoder-only MoE LM (Mixtral shape): call with mutable=['losses']
    to collect router aux losses. Construct with deterministic=False for
    training so the train capacity factor and router jitter apply."""

    config: LlamaMoEConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg = self.config
        embed = self.param(
            "embed",
            _logical(nn.initializers.normal(0.02), "vocab", "embed"),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype,
        )
        x = embed_lookup(embed, tokens, cfg)
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[-1]), tokens.shape)
        block_cls = MoEDecoderBlock
        if cfg.remat:
            block_cls = nn.remat(
                MoEDecoderBlock, static_argnums=(),
                policy=resolve_remat_policy(cfg.remat_policy),
            )
        for layer in range(cfg.num_layers):
            x = block_cls(cfg, deterministic=self.deterministic,
                          name=f"layer_{layer}")(x, positions)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl, name="final_norm")(x)
        head = self.param(
            "lm_head",
            _logical(nn.initializers.normal(0.02), "embed", "vocab"),
            (cfg.hidden_size, cfg.vocab_size), cfg.param_dtype,
        )
        return jnp.dot(x, head.astype(cfg.dtype)).astype(jnp.float32)


def moe_cross_entropy_loss(model: LlamaMoE, params: Any,
                           tokens: jax.Array,
                           targets: jax.Array) -> jax.Array:
    """Cross entropy + router aux losses in one scalar."""
    logits, mutables = model.apply({"params": params}, tokens,
                                   mutable=["losses"])
    return cross_entropy_loss(logits, targets) + moe_aux_loss(mutables)
