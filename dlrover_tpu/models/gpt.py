"""GPT-2 style model (nanoGPT-equivalent) in flax.linen.

Capability parity: the reference's end-to-end example model
(examples/pytorch/nanogpt/model.py, trained via ElasticTrainer in
examples/pytorch/nanogpt/train.py:289). Same logical-axis annotations as the
LLaMA family so every parallel strategy applies unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import dispatch_attention, embed_lookup
from dlrover_tpu.ops.remat import resolve_remat_policy


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    block_size: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "flash" | "reference" | "ring" | "ulysses" (the SP impls shard the
    # sequence dim over the mesh's `sequence` axis, as in LlamaConfig)
    attn_impl: str = "flash"
    # GPT is the single-host example family (nanogpt), so the cheap gather
    # lookup is the default; set "onehot" when training on a
    # (data, fsdp, tensor) mesh (see LlamaConfig.embed_impl for why).
    embed_impl: str = "gather"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @classmethod
    def nano(cls, **kw) -> "GPTConfig":
        kw.setdefault("vocab_size", 256)
        return cls(n_embd=128, n_layer=4, n_head=4, block_size=128, **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPTConfig":
        kw.setdefault("vocab_size", 128)
        return cls(n_embd=64, n_layer=2, n_head=2, block_size=64, **kw)


def _logical(init, *axes):
    return nn.with_logical_partitioning(init, axes)


class Block(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        batch, seq, _ = x.shape
        head_dim = cfg.n_embd // cfg.n_head

        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        qkv = nn.Dense(
            3 * cfg.n_embd, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "embed", "heads"),
            bias_init=_logical(nn.initializers.zeros, "heads"),
            name="qkv",
        )(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(batch, seq, cfg.n_head, head_dim)
                   for t in (q, k, v))
        attn = dispatch_attention(cfg.attn_impl, q, k, v, causal=True)
        attn = attn.reshape(batch, seq, cfg.n_embd)
        x = x + nn.Dense(
            cfg.n_embd, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "heads", "embed"),
            bias_init=_logical(nn.initializers.zeros, "embed"),
            name="attn_out",
        )(attn)

        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = nn.Dense(
            4 * cfg.n_embd, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "embed", "mlp"),
            bias_init=_logical(nn.initializers.zeros, "mlp"),
            name="fc",
        )(h)
        h = nn.gelu(h)
        x = x + nn.Dense(
            cfg.n_embd, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "mlp", "embed"),
            bias_init=_logical(nn.initializers.zeros, "embed"),
            name="proj",
        )(h)
        return x


class GPT(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg = self.config
        wte = self.param(
            "wte", _logical(nn.initializers.normal(0.02), "vocab", "embed"),
            (cfg.vocab_size, cfg.n_embd), cfg.param_dtype,
        )
        wpe = self.param(
            "wpe", _logical(nn.initializers.normal(0.02), None, "embed"),
            (cfg.block_size, cfg.n_embd), cfg.param_dtype,
        )
        seq = tokens.shape[-1]
        x = embed_lookup(wte, tokens, cfg) + wpe.astype(cfg.dtype)[:seq]
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block, static_argnums=(),
                policy=resolve_remat_policy(cfg.remat_policy),
            )
        for layer in range(cfg.n_layer):
            x = block_cls(cfg, name=f"block_{layer}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        # weight-tied LM head (as nanoGPT)
        return jnp.dot(x, wte.astype(cfg.dtype).T).astype(jnp.float32)
