"""Model families (flax.linen, logical-axis annotated).

Capability parity: the reference trains GPT-style models in its examples
(examples/pytorch/nanogpt/train.py) and large LLaMA/GLM-family models through
atorch (atorch/modules/transformer/layers.py LlamaAttentionFA etc.). Here:

- gpt.py   — nanoGPT-equivalent (LayerNorm, learned positions, GELU MLP)
- llama.py — LLaMA family (RMSNorm, RoPE, GQA, SwiGLU), the flagship for
  benchmarks; params carry logical axis names that
  dlrover_tpu.parallel.sharding maps onto the device mesh.
- bert.py  — BERT-family bidirectional encoder (masked LM, post-LN,
  flash attention with causal=False), ≙ the reference's Megatron BERT
  blocks + BertAttentionFA.
"""

from dlrover_tpu.models.bert import Bert, BertConfig, mlm_loss
from dlrover_tpu.models.gpt import GPT, GPTConfig
from dlrover_tpu.models.llama import Llama, LlamaConfig
