"""BERT-style bidirectional encoder (masked-LM) in flax.linen.

Capability parity: the reference's encoder model family — atorch ships
Megatron-parallel BERT blocks (atorch/modules/distributed_modules/
transformer.py:45, `BertAttentionFA` at modules/transformer/layers.py:740
pairs them with flash attention via module_replace). TPU re-design: the
same logical-axis annotations as the Llama/GPT families, so the whole
strategy table (fsdp/tensor/sequence/data) applies to encoders unchanged,
and the flash kernel runs with causal=False (full bidirectional
attention). Post-LN residuals as in original BERT.

Padding is handled the BERT way at the LOSS (masked positions carry
weight 0 in `mlm_loss`); the attention itself runs over the full padded
length — on TPU the rectangular kernel beats ragged masking for the
typical packed-sequence pretraining batches.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import dispatch_attention, embed_lookup
from dlrover_tpu.ops.remat import resolve_remat_policy


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "flash" | "reference" | "ring" | "ulysses" — all with causal=False
    # (long-context ENCODERS work too: the ring's online softmax never
    # needed causality, only Llama's defaults did)
    attn_impl: str = "flash"
    embed_impl: str = "gather"
    remat: bool = False
    remat_policy: str = "nothing_saveable"

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        kw.setdefault("vocab_size", 128)
        kw.setdefault("max_seq_len", 64)
        return cls(hidden_size=64, num_layers=2, num_heads=2,
                   intermediate_size=128, **kw)

    def param_count(self) -> int:
        h, i = self.hidden_size, self.intermediate_size
        per_layer = 4 * h * h + 2 * h * i
        return (self.vocab_size * h + self.max_seq_len * h
                + self.type_vocab_size * h
                + self.num_layers * per_layer + h * h)


def _logical(init, *axes):
    return nn.with_logical_partitioning(init, axes)


class EncoderBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        batch, seq, _ = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads

        qkv = nn.Dense(
            3 * cfg.hidden_size, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "embed", "heads"),
            bias_init=_logical(nn.initializers.zeros, "heads"),
            name="qkv",
        )(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(batch, seq, cfg.num_heads, head_dim)
                   for t in (q, k, v))
        attn = dispatch_attention(cfg.attn_impl, q, k, v, causal=False)
        attn = attn.reshape(batch, seq, cfg.hidden_size)
        attn = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "heads", "embed"),
            bias_init=_logical(nn.initializers.zeros, "embed"),
            name="attn_out",
        )(attn)
        # post-LN residuals (original BERT)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attn_norm")(x + attn)

        h = nn.Dense(
            cfg.intermediate_size, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "embed", "mlp"),
            bias_init=_logical(nn.initializers.zeros, "mlp"),
            name="fc",
        )(x)
        h = nn.gelu(h)
        h = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "mlp", "embed"),
            bias_init=_logical(nn.initializers.zeros, "embed"),
            name="proj",
        )(h)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="mlp_norm")(x + h)


class Bert(nn.Module):
    """Returns MLM logits (batch, seq, vocab) in fp32; weight-tied
    decoder over the word-embedding table."""

    config: BertConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 token_types: jax.Array | None = None) -> jax.Array:
        cfg = self.config
        word = self.param(
            "word_embed",
            _logical(nn.initializers.normal(0.02), "vocab", "embed"),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype,
        )
        pos = self.param(
            "pos_embed",
            _logical(nn.initializers.normal(0.02), None, "embed"),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype,
        )
        typ = self.param(
            "type_embed",
            _logical(nn.initializers.normal(0.02), None, "embed"),
            (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype,
        )
        seq = tokens.shape[-1]
        x = embed_lookup(word, tokens, cfg) + pos.astype(cfg.dtype)[:seq]
        if token_types is not None:
            x = x + typ.astype(cfg.dtype)[token_types]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embed_norm")(x)
        block_cls = EncoderBlock
        if cfg.remat:
            block_cls = nn.remat(
                EncoderBlock, static_argnums=(),
                policy=resolve_remat_policy(cfg.remat_policy),
            )
        for layer in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{layer}")(x)
        # MLM head: dense transform + LN + tied decoder (BERT's
        # cls/predictions/transform)
        x = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            # square transform: column-parallel-style split on the output
            # ("mlp" -> tensor axis); a logical name may appear only once
            # per array, so the input dim rides fsdp-free
            kernel_init=_logical(nn.initializers.normal(0.02),
                                 "embed", "mlp"),
            bias_init=_logical(nn.initializers.zeros, "mlp"),
            name="mlm_transform",
        )(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_norm")(x)
        return jnp.dot(x, word.astype(cfg.dtype).T).astype(jnp.float32)


def mlm_loss(logits: jax.Array, targets: jax.Array,
             weights: jax.Array | None = None) -> jax.Array:
    """Masked-LM cross entropy. `weights` marks the PREDICTED positions
    (1 at [MASK]-ed tokens, 0 elsewhere/padding); None scores all
    positions (the dense-target convenience used by tests)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None],
                               axis=-1).squeeze(-1)
    if weights is None:
        return nll.mean()
    weights = weights.astype(nll.dtype)
    return (nll * weights).sum() / jnp.maximum(weights.sum(), 1.0)
