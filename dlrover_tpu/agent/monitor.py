"""Agent-side monitors: node resources, training progress, hang detection.

Capability parity:
- `ResourceMonitor` ≙ elastic_agent/monitor/resource.py:86 (psutil +
  pynvml → here psutil + jax TPU memory_stats) reporting every 15 s;
- `TrainingMonitor` ≙ elastic_agent/monitor/training.py:78
  (TorchTrainingMonitor reads a metrics file the training process appends
  to and forwards global step to the master);
- `HangingDetector` ≙ atorch/fault_tolerance/hanging_detector.py:86
  (heartbeat thread + no-progress window ⇒ restart workers).

The training process writes `{"step": N, "ts": ...}` JSON lines to the
metrics file named by `NodeEnv.METRICS_FILE` (the `report_step` helper);
the agent-side monitors never import jax into the training process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.obs.device import _RISE_THRESHOLD_BYTES


def report_step(step: int, path: Optional[str] = None,
                step_time_s: float = 0.0,
                data_wait_fraction: float = -1.0,
                plan_generation: int = -1) -> None:
    """Called from the TRAINING process each step (or every k steps).
    Atomic single-record write: readers only ever need the latest record,
    and week-long jobs must not grow the file unboundedly. The optional
    timing fields (windowed mean step time + data-wait fraction, from
    the phase timeline) ride along so the agent's TrainingMonitor can
    forward the diagnosis engine's straggler evidence.
    ``plan_generation``: the shard-plan generation the trainer actually
    applied (parallel/planner.py) — forwarded so the master's plan
    calibration attributes this timing to the right mesh shape; -1 =
    sender does not track plans (calibration falls back to
    current-signature attribution); -2 = sender ran a fallback mesh
    (the master DROPS the evidence — it must ride the relay, not
    collapse into -1's current-shape attribution)."""
    path = path or os.environ.get(NodeEnv.METRICS_FILE, "")
    if not path:
        return
    record = {"step": int(step), "ts": time.time()}
    if step_time_s > 0.0:
        record["step_time_s"] = float(step_time_s)
    if data_wait_fraction >= 0.0:
        record["data_wait_fraction"] = float(data_wait_fraction)
    if plan_generation != -1:
        record["plan_generation"] = int(plan_generation)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(record) + "\n")
    os.replace(tmp, path)


def _read_last_step(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 4096))
            lines = f.read().decode(errors="ignore").strip().splitlines()
        for line in reversed(lines):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    except OSError:
        return None
    return None


class ResourceMonitor:
    """Report host cpu/mem + TPU chip stats to the master periodically."""

    def __init__(self, client: MasterClient, node_type: str = "worker",
                 interval_s: Optional[float] = None,
                 chip_stats_file: str = ""):
        self._client = client
        self._node_type = node_type
        self._interval_s = (interval_s if interval_s is not None
                            else Context.singleton()
                            .report_resource_interval_s)
        # explicit path wins; env is the worker-process export contract
        self._chip_stats_file = chip_stats_file
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # prime psutil's CPU sampler: cpu_percent(interval=None) computes
        # utilization SINCE THE LAST CALL and returns a meaningless 0.0
        # on its first — one throwaway call here makes every sample()
        # real (an all-zero first report reads as an idle node)
        try:
            import psutil

            psutil.cpu_percent(interval=None)
        except ImportError:
            pass

    def sample(self) -> msg.NodeResourceStats:
        cpu_percent = 0.0
        memory_mb = 0.0
        try:
            import psutil

            cpu_percent = psutil.cpu_percent(interval=None)
            memory_mb = psutil.virtual_memory().used / (1 << 20)
        except ImportError:  # psutil is present in the image; belt+braces
            pass
        stats = msg.NodeResourceStats(
            node_id=self._client.node_id,
            node_type=self._node_type,
            cpu_percent=cpu_percent,
            memory_mb=memory_mb,
            node_rank=getattr(self._client, "node_rank", -1),
            chip_stats=self._chip_stats(),
        )
        # same series the master exposes, in the agent's own registry
        # (local debugging; the RPC report remains the master-side feed)
        obs.publish_node_stats(stats)
        return stats

    def _chip_stats(self) -> List[msg.ChipStats]:
        """TPU HBM usage via jax memory_stats (the pynvml analog). Only
        meaningful in a process that owns the chips; the agent reads a
        stats file exported by the worker when available."""
        path = (self._chip_stats_file
                or os.environ.get(NodeEnv.CHIP_STATS_FILE, ""))
        if not path or not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                raw = json.load(f)
            return [msg.ChipStats(**chip) for chip in raw]
        except (OSError, json.JSONDecodeError, TypeError):
            return []

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="resource-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self._client.report_resource_stats(self.sample())
                self._client.report_heartbeat()
            except Exception as e:  # noqa: BLE001 - monitoring best-effort
                logger.warning("resource report failed: %s", e)


# last export's (wall time, step): the duty-cycle proxy needs a delta
# to derive busy time from. One training process = one exporter, so a
# module-level cell (no lock: only the step loop calls this) suffices.
_chip_export_prev: dict = {}
# last exported peak_bytes_in_use per (path, device): the allocator
# counter is lifetime-monotone (obs/device.py), so the export must
# window it — relaying the raw counter would latch HbmPressureRule on
# a long-resolved spike forever. Same noise threshold as the step-
# report path, imported so the two windowings cannot drift.
_chip_export_peaks: dict = {}
_PEAK_RISE_BYTES = _RISE_THRESHOLD_BYTES


def export_chip_stats(path: Optional[str] = None,
                      step: Optional[int] = None,
                      step_time_s: float = 0.0) -> None:
    """Called from the TRAINING process: dump per-chip HBM usage for the
    agent's ResourceMonitor to relay.

    Duty cycle: jax exposes no per-chip utilization counter, so a proxy
    is derived from consecutive exports — steps completed since the last
    export × the per-step DEVICE-BUSY seconds (``step_time_s``: mean
    step time minus the host-starve phases; the caller derives it from
    the phase timeline — total step time here would read ≈ 100% even
    when the chips idle on a stalled input pipeline), over the
    wall-clock elapsed. Callers that cannot supply (step, step_time_s)
    get stats WITHOUT the field — an honest absence instead of a
    hardcoded 0.0."""
    path = path or os.environ.get(NodeEnv.CHIP_STATS_FILE, "")
    if not path:
        return
    import jax

    now = time.time()
    duty: Optional[float] = None
    prev = _chip_export_prev.get(path)
    if step is not None and step_time_s > 0.0 and prev is not None:
        elapsed = now - prev["ts"]
        steps_done = step - prev["step"]
        if elapsed > 0 and steps_done >= 0:
            duty = min(100.0, 100.0 * steps_done * step_time_s / elapsed)
    if step is not None:
        _chip_export_prev[path] = {"ts": now, "step": int(step)}
    stats = []
    peaks = _chip_export_peaks.setdefault(path, {})
    for device in jax.local_devices():
        try:
            mem = device.memory_stats() or {}
        except Exception:  # noqa: BLE001 — backend support varies
            mem = {}
        chip = {"index": device.id}
        if mem:
            # hbm fields only when the backend actually answered: a CPU
            # backend's absent memory_stats used to export hbm_used_mb=0
            # forever — a 0 % series dashboards read as real headroom
            # instead of an honest absence
            chip["hbm_used_mb"] = mem.get("bytes_in_use", 0) / (1 << 20)
            chip["hbm_total_mb"] = mem.get("bytes_limit", 0) / (1 << 20)
            # the allocator's peak high-water mark: the transient
            # IN-step peak the between-steps bytes_in_use sample misses
            # (obs/device.py; what HbmPressureRule should judge).
            # Exported only when it ROSE since the last export — the
            # counter never resets within a process, so relaying it
            # unconditionally would keep a long-resolved spike in
            # HbmPressureRule's evidence forever; between rises,
            # hbm_used_mb is the honest live signal (the same
            # windowing DeviceTelemetry applies to the step report)
            peak = float(mem.get("peak_bytes_in_use", 0) or 0)
            prev_peak = peaks.get(device.id, 0.0)
            if peak > prev_peak + _PEAK_RISE_BYTES:
                chip["hbm_peak_mb"] = peak / (1 << 20)
            peaks[device.id] = max(peak, prev_peak)
        if duty is not None:
            chip["duty_cycle_pct"] = duty
        stats.append(chip)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(stats, f)
    os.replace(tmp, path)


class TrainingMonitor:
    """Tail the worker's metrics file; forward global step to the master."""

    def __init__(self, client: MasterClient, metrics_file: str,
                 interval_s: float = 15.0):
        self._client = client
        self._metrics_file = metrics_file
        self._interval_s = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_reported = -1

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="training-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def last_progress_time(self) -> float:
        record = _read_last_step(self._metrics_file)
        return record["ts"] if record else 0.0

    def _loop(self) -> None:
        step_gauge = obs.get_registry().gauge(
            "dlrover_tpu_agent_reported_step",
            "Last worker step this agent forwarded to the master")
        while not self._stopped.wait(self._interval_s):
            record = _read_last_step(self._metrics_file)
            if record and record["step"] > self._last_reported:
                self._last_reported = record["step"]
                step_gauge.set(record["step"])
                try:
                    # forward the worker's timing evidence when the
                    # record carries it (diagnosis straggler input)
                    self._client.report_global_step(
                        record["step"],
                        step_time_s=float(
                            record.get("step_time_s", 0.0) or 0.0),
                        data_wait_fraction=float(
                            record.get("data_wait_fraction", -1.0)),
                        plan_generation=int(
                            record.get("plan_generation", -1)),
                    )
                except Exception as e:  # noqa: BLE001
                    logger.warning("step report failed: %s", e)


class HangingDetector:
    """Restart the worker when no step progress for `hang_seconds`
    (atorch --relaunch_on_hanging analog)."""

    def __init__(
        self,
        metrics_file: str,
        on_hang: Callable[[], None],
        hang_seconds: Optional[float] = None,
        check_interval_s: float = 30.0,
        warmup_s: float = 300.0,
    ):
        self._metrics_file = metrics_file
        self._on_hang = on_hang
        self._hang_seconds = (hang_seconds if hang_seconds is not None
                              else Context.singleton().hang_seconds)
        self._check_interval_s = check_interval_s
        self._warmup_s = warmup_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the grace-period clock is reset from the agent thread
        # (worker restart) while the detector thread reads it
        self._clock_lock = threading.Lock()
        self._started_at = time.time()

    def start(self) -> None:
        with self._clock_lock:
            self._started_at = time.time()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hang-detector")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def reset(self) -> None:
        """Call after a worker restart (fresh compile grace period)."""
        with self._clock_lock:
            self._started_at = time.time()

    def is_hanged(self) -> bool:
        record = _read_last_step(self._metrics_file)
        now = time.time()
        with self._clock_lock:
            started_at = self._started_at
        if record is None:
            # no step ever: hang only after warmup (first compile is slow)
            return now - started_at > max(self._warmup_s,
                                          self._hang_seconds)
        # a stale record from before the last (re)start must not re-fire:
        # progress is the newer of last-step time and last restart time
        last_progress = max(record["ts"], started_at)
        return now - last_progress > self._hang_seconds

    def _loop(self) -> None:
        while not self._stopped.wait(self._check_interval_s):
            if self.is_hanged():
                logger.error("hang detected: no step progress for %.0fs",
                             self._hang_seconds)
                try:
                    self._on_hang()
                finally:
                    self.reset()


class ParalConfigTuner:
    """Poll the master's tuned ParallelConfig and write it to the JSON
    file the ElasticDataLoader hot-reloads (reference:
    elastic_agent/config/paral_config_tuner.py:30-60)."""

    def __init__(self, client: MasterClient, config_path: str,
                 interval_s: float = 30.0):
        self._client = client
        self._config_path = config_path
        self._interval_s = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # poll_once runs on the tuner thread and directly from tests /
        # agent shutdown: the version check-and-set must be atomic
        self._version_lock = threading.Lock()
        self._last_version = -1

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paral-config-tuner")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def poll_once(self) -> bool:
        config = self._client.get_paral_config()
        with self._version_lock:
            if config.version <= self._last_version:
                return False
            self._last_version = config.version
        tmp = self._config_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "version": config.version,
                "dataloader_batch_size": config.dataloader_batch_size,
                "dataloader_workers": config.dataloader_workers,
                "learning_rate": config.learning_rate,
                "grad_accum_steps": config.grad_accum_steps,
            }, f)
        os.replace(tmp, self._config_path)  # atomic for the reader
        return True

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001
                logger.warning("paral config poll failed: %s", e)
