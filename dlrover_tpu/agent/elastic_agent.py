"""Node-side elastic agent: rendezvous, spawn, monitor, restart.

Capability parity: dlrover/python/elastic_agent/torch/training.py —
``ElasticTrainingAgent`` (rendezvous :315, monitor/restart loop :429-521,
failure reporting :490) re-designed for JAX workers:

- One agent per TPU host. The worker it spawns is ONE JAX process that owns
  all local chips (torch spawns one proc per GPU; JAX is one proc per host).
- Rendezvous yields {node_rank → local chip count}; the agent derives
  ``jax.distributed`` (num_processes, process_id) and the round's coordinator
  address, published through the master KV store (replacing the reference's
  MasterKVStore/c10d bootstrap, elastic_agent/torch/master_kv_store.py).
- On worker failure: report to master, re-rendezvous, respawn (restart
  budget). On membership change (``num_nodes_waiting > 0``): graceful
  restart so the world re-forms — training re-lowers to the new mesh.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import grpc

from dlrover_tpu import obs
from dlrover_tpu.agent.master_client import MasterClient, backoff_delay_s
from dlrover_tpu.agent.preemption import (
    PreemptionNotice,
    PreemptionWatcher,
    default_sources,
    write_drain_request,
)
from dlrover_tpu.common.bootstrap import publish_or_wait_coordinator
from dlrover_tpu.common.constants import (
    DefaultValues,
    NodeEnv,
    NodeExitReason,
    RendezvousName,
    TrainingMsgLevel,
    WorkerExit,
)
from dlrover_tpu.common.log import default_logger as logger


class RelaunchGovernor:
    """Per-rank relaunch pacing: exponential delay between worker
    relaunches (base·2^(k−1) for the k-th recent failure, capped — no
    jitter: one agent, one worker, nothing to de-synchronize) and
    quarantine once ``quarantine_failures`` land inside
    ``quarantine_window_s``. A flapping worker must not hot-loop
    respawns. Driven only from the agent's main run loop — the same
    single-writer contract as the worker process itself, so no lock.

    Hang-aborts do not charge ``max_restarts``, so they need their own
    loop-breaker the time window cannot provide (a watchdog cycle of a
    few minutes never fits ``quarantine_failures`` aborts inside the
    window): ``record_hang`` counts CONSECUTIVE hangs from incarnations
    that made no forward progress. Progress is judged two ways — the
    incarnation pushed the job's step high-water mark (the timeline
    export the agent reads; re-treading checkpointed steps is NOT
    forward progress), or it outlived the watchdog's warmup-plus-slack
    horizon (the watchdog would have fired sooner otherwise). Either
    one — on ANY death, hang or crash — resets the streak, so hangs
    separated by productive incarnations never accumulate.
    ``quarantine_failures`` no-progress hangs in a row quarantine the
    rank regardless of how slowly they arrive."""

    def __init__(self, clock=time.monotonic):
        from collections import deque

        from dlrover_tpu.common.config import Context
        from dlrover_tpu.trainer.watchdog import default_warmup_s

        ctx = Context.singleton()
        self._base_s = ctx.relaunch_backoff_base_s
        self._max_s = ctx.relaunch_backoff_max_s
        self._quarantine_failures = ctx.quarantine_failures
        self._window_s = ctx.quarantine_window_s
        # the watchdog's own first-step budget plus 2·hang of slack: an
        # incarnation alive past this has stepped even if the timeline
        # export never landed
        hang_s = ctx.hang_watchdog_s
        self._hang_progress_horizon_s = (default_warmup_s(hang_s)
                                         + 2.0 * hang_s)
        self._consecutive_early_hangs = 0
        self._clock = clock
        self._failures = deque()

    def _trim(self, now: float) -> None:
        while self._failures and now - self._failures[0] > self._window_s:
            self._failures.popleft()

    def _note_progress(self, lifetime_s: float,
                       made_progress: bool) -> None:
        if (made_progress
                or lifetime_s >= self._hang_progress_horizon_s):
            self._consecutive_early_hangs = 0

    def record_failure(self, lifetime_s: float = 0.0,
                       made_progress: bool = False) -> float:
        """Register one worker failure (any kind); returns the backoff
        delay to apply before the relaunch. A productive incarnation —
        stepped past the job high-water mark, or simply long-lived —
        breaks the no-progress hang streak even when it ends in a
        crash: its hangs were never 'consecutive'."""
        self._note_progress(lifetime_s, made_progress)
        now = self._clock()
        self._trim(now)
        self._failures.append(now)
        exponent = min(len(self._failures) - 1, 62)
        return min(self._max_s, self._base_s * (2.0 ** exponent))

    def record_hang(self, lifetime_s: float,
                    made_progress: bool = False) -> None:
        """Register a watchdog hang-abort. Counts toward the streak
        only when the incarnation made NO forward progress — a worker
        that advanced the job before wedging is a flaky collective,
        not a deterministic hang loop."""
        if (made_progress
                or lifetime_s >= self._hang_progress_horizon_s):
            self._consecutive_early_hangs = 0
        else:
            self._consecutive_early_hangs += 1

    @property
    def recent_failures(self) -> int:
        self._trim(self._clock())
        return len(self._failures)

    @property
    def quarantined(self) -> bool:
        if self._quarantine_failures <= 0:
            return False
        return (self.recent_failures >= self._quarantine_failures
                or (self._consecutive_early_hangs
                    >= self._quarantine_failures))


@dataclasses.dataclass
class WorkerSpec:
    """What to run on this node."""

    entrypoint: List[str]                    # argv of the training process
    devices_per_node: int = 1                # local chip count
    max_restarts: int = DefaultValues.MAX_RELAUNCH
    monitor_interval_s: float = DefaultValues.MONITOR_INTERVAL_S
    rdzv_timeout_s: float = DefaultValues.RDZV_TIMEOUT_S
    # SIGTERM → SIGKILL grace: must cover one train step + a forced
    # checkpoint commit (the worker saves on SIGTERM, elastic_loop.py).
    shutdown_grace_s: float = 120.0
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    # side monitors (resource/step reporting, tuned-config polling)
    enable_monitors: bool = True
    # restart the worker when step progress stalls (atorch
    # --relaunch_on_hanging analog)
    relaunch_on_hanging: bool = False
    # consecutive failed num_nodes_waiting polls (each already a full
    # retry_rpc budget) before declaring the master lost and entering
    # the degraded reconnect loop
    master_lost_after_polls: int = 2

    def __post_init__(self) -> None:
        # THIS interval (not Context.monitor_interval_s, an independent
        # master-side knob) paces the agent's num_nodes_waiting poll —
        # the master's main liveness signal. A dead-node timeout under
        # ~3 polls reaps healthy agents that merely missed one tick.
        from dlrover_tpu.common.config import Context

        timeout = Context.singleton().dead_node_timeout_s
        if 0 < timeout < 3 * self.monitor_interval_s:
            logger.warning(
                "dead_node_timeout_s (%.0fs) < 3x the agent poll "
                "interval (--monitor-interval %.0fs): healthy agents "
                "may be declared dead between polls; raise the timeout "
                "or lower the poll interval",
                timeout, self.monitor_interval_s)


class RendezvousTimeoutError(TimeoutError):
    pass


class MasterLostError(RuntimeError):
    """The master stayed unreachable past the reconnect budget."""


class PreemptedDuringOutage(Exception):
    """A preemption notice arrived while the agent was in master-lost
    reconnect: the reconnect is abandoned so the grace window goes to
    the local emergency checkpoint, not to dialing a dead master."""


class ElasticAgent:
    """Joins the master rendezvous and keeps one training process alive."""

    def __init__(self, client: MasterClient, spec: WorkerSpec,
                 rdzv_name: str = RendezvousName.TRAINING):
        self._client = client
        self._spec = spec
        self._rdzv_name = rdzv_name
        self._restart_count = 0
        self._master_fail_streak = 0
        # set by shutdown(): the run loop must not resurrect the worker
        # it just killed, and reconnect loops must stop dialing
        self._shutdown = threading.Event()
        self._proc: Optional[subprocess.Popen] = None
        self.last_world: Dict[int, int] = {}
        self.last_round = -1
        self._monitors: List = []
        self._hang_detector = None
        # set by the HangingDetector thread; consumed (and acted on) only
        # by the main run() loop so worker restarts never race
        self._hang_event = threading.Event()
        self._workdir = tempfile.mkdtemp(prefix="dlrover-tpu-agent-")
        self.metrics_file = os.path.join(self._workdir, "metrics.jsonl")
        self.chip_stats_file = os.path.join(self._workdir, "chips.json")
        self.paral_config_file = os.path.join(self._workdir, "paral.json")
        # diagnosis plumbing: the worker exports its per-step phase
        # timeline here, and picks up on-demand profiler captures the
        # agent requests when executing a master `profile:{rank}` action
        self.timeline_file = os.path.join(self._workdir, "timeline.json")
        self.profile_request_file = os.path.join(
            self._workdir, "profile_request.json")
        self.profile_dump_dir = os.path.join(self._workdir, "profiles")
        self._profile_request_seq = 0
        # preemption drain plumbing: the notice file chaos/platform
        # hooks write (PreemptionWatcher polls it; honored from env so
        # a platform hook outside this agent can name the path), and
        # the drain request the worker's step loop consumes
        self.preempt_notice_file = os.environ.get(
            NodeEnv.PREEMPTION_NOTICE_FILE,
            os.path.join(self._workdir, "preempt_notice.json"))
        self.drain_request_file = os.path.join(
            self._workdir, "drain_request.json")
        self._drain_seq = 0
        # set by the PreemptionWatcher thread; consumed only by the main
        # run loop (same contract as _hang_event)
        self._preempt_notice: Optional[PreemptionNotice] = None
        self._preempt_event = threading.Event()
        self._preempt_watcher: Optional[PreemptionWatcher] = None
        # peer-to-peer restore plumbing (checkpoint/peer_restore.py):
        # the worker stages its state here at checkpoint boundaries; the
        # donor server (started in run(), owned by THIS process so it
        # survives worker restarts) serves it to replacement ranks, and
        # the join-result restore plan lands in the plan file for the
        # worker
        self.peer_cache_dir = os.path.join(self._workdir, "peer_cache")
        self.restore_plan_file = os.path.join(self._workdir,
                                              "restore_plan.json")
        # online parallelism re-plan from the join result
        # (parallel/planner.py): the spawned worker builds its mesh +
        # batch shape from this file (or re-fetches fresh via RPC)
        self.shard_plan_file = os.path.join(self._workdir,
                                            "shard_plan.json")
        self._peer_donor = None
        # (ino, mtime_ns, size) of the manifest at the last report —
        # the same stat-key dedup contract as the drain channel, so the
        # monitor tick never re-parses an unchanged manifest
        self._peer_reported_statkey: Optional[Tuple] = None
        # relaunch pacing: backoff between respawns, quarantine on flap
        self._governor = RelaunchGovernor()
        self._spawn_ts = time.monotonic()
        # the job's step high-water mark at spawn (from the timeline
        # export): an incarnation that pushes past it made FORWARD
        # progress — re-treading checkpointed steps does not count
        self._spawn_step = -1
        # Persistent XLA compile cache shared across worker restarts: an
        # elastic restart re-lowers the same programs, so the respawned
        # worker skips compilation — the dominant cost of a fast restore.
        self.compile_cache_dir = os.path.join(self._workdir, "xla-cache")
        # batches the agent's finished spans (rendezvous etc.) for the
        # master's job-wide timeline; flushed from the monitor loop
        self._span_exporter = obs.SpanExporter()
        obs.add_span_sink(self._span_exporter)

    # -- rendezvous --------------------------------------------------------
    def rendezvous(self) -> Tuple[int, Dict[int, int]]:
        """Join and poll until this node is in a completed world
        (reference: MasterRendezvousHandler.next_rendezvous training.py:180).
        """
        spec = self._spec
        # the agent-side rendezvous span is the trace root: the join RPC
        # carries its context, so the master's rendezvous_join span (and
        # everything the master hangs beneath it) shares this trace
        with obs.span("rendezvous",
                      {"rdzv": self._rdzv_name,
                       "rank": self._client.node_rank}) as rdzv_span:
            # advertise this host's staged state BEFORE joining: a
            # replacement rank's plan (computed at its own join) must be
            # able to name this survivor as a donor
            self._report_peer_store(force=True)
            joined_round = self._client.join_rendezvous(
                spec.devices_per_node, self._rdzv_name)
            self._publish_restore_plan()
            deadline = time.time() + spec.rdzv_timeout_s
            while time.time() < deadline:
                rdzv_round, _, world = self._client.get_comm_world(
                    self._rdzv_name
                )
                if world and self._client.node_rank in world:
                    self.last_world, self.last_round = world, rdzv_round
                    rdzv_span.set_attr("round", rdzv_round)
                    rdzv_span.set_attr("world_size", len(world))
                    return rdzv_round, world
                if rdzv_round > joined_round:
                    # Our round was cut without us — the world was
                    # invalidated by a member death, or node_unit rounding
                    # dropped us. Re-join so the next round can include
                    # this node.
                    logger.info(
                        "rendezvous round %d passed without this node; "
                        "re-joining", joined_round,
                    )
                    joined_round = self._client.join_rendezvous(
                        spec.devices_per_node, self._rdzv_name)
                time.sleep(0.5)
            raise RendezvousTimeoutError(
                f"rendezvous {self._rdzv_name!r} did not complete within "
                f"{spec.rdzv_timeout_s:.0f}s"
            )

    def _bootstrap_env(self, rdzv_round: int,
                       world: Dict[int, int]) -> Dict[str, str]:
        """Derive the JAX process set for this round; the lowest rank
        publishes the coordinator address via the master KV store."""
        ranks = sorted(world)
        process_id = ranks.index(self._client.node_rank)
        slice_id = self._client.slice_id
        # slice mode: each slice is its own jax world with its own
        # per-slice round counter — the coordinator key must be scoped
        # by slice or two slices cutting round N would collide
        coord_key = (f"coord/{self._rdzv_name}/slice{slice_id}/"
                     f"{rdzv_round}" if slice_id >= 0
                     else f"coord/{self._rdzv_name}/{rdzv_round}")
        coord = publish_or_wait_coordinator(
            self._client, coord_key,
            process_id, self._spec.rdzv_timeout_s,
        )
        env = dict(os.environ)
        env.update(self._spec.env)
        env.update({
            NodeEnv.MASTER_ADDR: self._client.master_addr,
            # the coordination tier the join result advertised ("" =
            # single-tier): the worker's hot dcn/ traffic dials it
            # directly (master/coord_service.py)
            NodeEnv.COORD_ADDR: self._client.coord_addr,
            NodeEnv.NODE_ID: str(self._client.node_id),
            NodeEnv.NODE_RANK: str(self._client.node_rank),
            NodeEnv.WORLD_SIZE: str(len(ranks)),
            NodeEnv.PROCESS_ID: str(process_id),
            NodeEnv.COORDINATOR_ADDR: coord,
            NodeEnv.RDZV_ROUND: str(rdzv_round),
            NodeEnv.DEVICES_PER_NODE: str(self._spec.devices_per_node),
            NodeEnv.METRICS_FILE: self.metrics_file,
            NodeEnv.CHIP_STATS_FILE: self.chip_stats_file,
            NodeEnv.PARAL_CONFIG_PATH: self.paral_config_file,
            NodeEnv.TIMELINE_FILE: self.timeline_file,
            NodeEnv.PROFILE_REQUEST_FILE: self.profile_request_file,
            NodeEnv.DRAIN_REQUEST_FILE: self.drain_request_file,
            NodeEnv.PEER_CACHE_DIR: self.peer_cache_dir,
            NodeEnv.RESTORE_PLAN_FILE: self.restore_plan_file,
            NodeEnv.SHARD_PLAN_FILE: self.shard_plan_file,
            # the worker sees the same notice path the agent polls, so
            # the chaos `preempt` fault (running in the worker's step
            # loop) can deliver a notice to THIS agent deterministically
            NodeEnv.PREEMPTION_NOTICE_FILE: self.preempt_notice_file,
            # the worker's slice identity: gates the cross-slice
            # gradient sync and slice-targeted chaos faults
            NodeEnv.SLICE_ID: str(slice_id),
        })
        env.setdefault("JAX_COMPILATION_CACHE_DIR", self.compile_cache_dir)
        return env

    # -- worker lifecycle --------------------------------------------------
    def _spawn(self) -> None:
        rdzv_round, world = self.rendezvous()
        env = self._bootstrap_env(rdzv_round, world)
        logger.info(
            "spawning worker (round %d, world %s, restart %d): %s",
            rdzv_round, sorted(world), self._restart_count,
            self._spec.entrypoint,
        )
        self._proc = subprocess.Popen(self._spec.entrypoint, env=env)
        self._spawn_ts = time.monotonic()
        self._spawn_step = self._timeline_step()
        obs.get_flight_recorder().record_event(
            "worker_spawn", round=rdzv_round, world=sorted(world),
            restart=self._restart_count, pid=self._proc.pid)

    def _stop_worker(self) -> None:
        if self._proc is None or self._proc.poll() is not None:
            return
        self._proc.send_signal(signal.SIGTERM)
        try:
            self._proc.wait(self._spec.shutdown_grace_s)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()

    def _restart_worker_resilient(self, count_against_budget: bool
                                  ) -> None:
        """_restart_worker, but a restart whose own rendezvous cannot
        reach the master falls into master-lost handling: a worker crash
        DURING a master outage gets the full reconnect budget
        (master_reconnect_timeout_s), not just one RPC retry budget.
        After reconnection the resync sees the dead worker and respawns
        it. ONLY transport errors divert: a RendezvousTimeoutError
        (master answered, world never formed) or a spawn failure
        (Popen OSError — the entrypoint itself is broken, and retrying
        against a healthy master would loop forever) propagates."""
        try:
            self._restart_worker(count_against_budget)
        except grpc.RpcError as exc:
            logger.warning(
                "worker restart could not reach the master (%s); "
                "entering master-lost mode", exc)
            self._handle_master_loss()

    def _restart_worker(self, count_against_budget: bool) -> None:
        """Membership-change restarts are normal elasticity and do NOT
        consume the failure budget (reference: torchelastic only charges
        the budget on the failure path)."""
        self._stop_worker()
        if count_against_budget:
            self._restart_count += 1
        self._spawn()
        self._hang_event.clear()  # a stale flag must not re-kill the
        # fresh worker (e.g. hang flagged, then crash-path restarted)
        if self._hang_detector is not None:
            self._hang_detector.reset()  # fresh compile grace period

    def _start_monitors(self) -> None:
        if not self._spec.enable_monitors:
            return
        from dlrover_tpu.agent.monitor import (
            HangingDetector,
            ParalConfigTuner,
            ResourceMonitor,
            TrainingMonitor,
        )

        self._monitors = [
            ResourceMonitor(self._client,
                            chip_stats_file=self.chip_stats_file),
            TrainingMonitor(self._client, self.metrics_file),
            ParalConfigTuner(self._client, self.paral_config_file),
        ]
        if self._spec.relaunch_on_hanging:
            self._hang_detector = HangingDetector(
                self.metrics_file,
                on_hang=self._hang_event.set,
            )
            self._monitors.append(self._hang_detector)
        for monitor in self._monitors:
            monitor.start()

    def _stop_monitors(self) -> None:
        for monitor in self._monitors:
            monitor.stop()
        self._monitors = []

    # -- main loop ---------------------------------------------------------
    def run(self) -> int:
        """Monitor loop (reference: _invoke_run training.py:429-521).
        Returns the worker's final exit code."""
        recorder = obs.get_flight_recorder()
        # ORDER MATTERS: the drain SIGTERM source installs first, the
        # recorder's dump handler second — the recorder chains to its
        # predecessor, so one SIGTERM yields BOTH the flight dump and
        # the drain notice (and nobody re-raises the default kill: the
        # notice is the graceful alternative to dying now)
        self._start_preemption_watcher()
        self._start_peer_donor()
        if threading.current_thread() is threading.main_thread():
            # postmortem timeline even when the platform SIGTERMs the
            # agent itself (signal API is main-thread-only)
            recorder.install_signal_handlers()
        recorder.install_excepthook()
        self._spawn()
        self._start_monitors()
        try:
            # normalize at the process boundary: a worker code this
            # agent re-exits with must be POSIX-shaped (134, not -6) or
            # the pod-side classification can never recognize it
            return WorkerExit.to_exit_status(self._run_loop())
        except BaseException:
            # master-lost (and only master-lost) paths can raise with a
            # LIVE worker — never orphan the trainer on the way out
            self._stop_worker()
            raise
        finally:
            self._stop_monitors()
            if self._preempt_watcher is not None:
                self._preempt_watcher.stop()
            self._stop_peer_donor()
            self._flush_telemetry()
            obs.remove_span_sink(self._span_exporter)
            recorder.dump(reason="agent-exit")

    def _flush_telemetry(self) -> None:
        self._span_exporter.flush_to(self._client)

    def _interruptible_wait(self, delay_s: float) -> None:
        """Sleep up to ``delay_s``, returning early on shutdown or a
        preemption notice — every sleep on the agent's main loop sits
        inside the grace window, and the window is short."""
        end = time.monotonic() + delay_s
        while (time.monotonic() < end
               and not self._shutdown.is_set()
               and not self._preempt_event.is_set()):
            time.sleep(min(0.2, max(0.0, end - time.monotonic())))

    def _run_loop(self) -> int:
        spec = self._spec
        while True:
            self._interruptible_wait(spec.monitor_interval_s)
            if self._shutdown.is_set():
                return 0
            # a preemption notice outranks everything: this host is
            # going away — drain instead of monitoring
            if self._preempt_event.is_set():
                return self._drain(self._preempt_notice)
            self._flush_telemetry()
            code = self._proc.poll()
            if code is not None:
                if self._shutdown.is_set():
                    return 0
                if code == 0:
                    logger.info("worker finished successfully")
                    return 0
                kind = WorkerExit.classify(
                    code, hang_enabled=self._hang_watchdog_enabled())
                if kind == NodeExitReason.DRAINED:
                    # the worker drained itself (its own SIGTERM path or
                    # a notice the agent never saw): clean departure —
                    # no failure report, no relaunch charge
                    return self._conclude_drain(code, deadline=0.0,
                                                reason="worker-initiated")
                outcome = self._handle_worker_failure(code, kind)
                if outcome is not None:
                    return outcome
                continue
            # Hang flagged by the detector thread: restart HERE so only
            # the main loop ever touches the worker process.
            if self._hang_event.is_set():
                self._hang_event.clear()
                logger.error("restarting hanged worker")
                obs.get_flight_recorder().record_event("worker_hang")
                self._restart_worker_resilient(count_against_budget=False)
                continue
            # Healthy: check membership first, then execute any
            # diagnosis actions the master queued for this rank
            # (reference: training.py:483-486,510-521). Actions are
            # polled only after a SUCCESSFUL liveness probe: during a
            # master outage an extra un-retried RPC here would block a
            # full timeout per tick before the probe that actually
            # advances the master-lost streak.
            try:
                waiting = self._client.num_nodes_waiting(self._rdzv_name)
                self._master_fail_streak = 0
            except Exception:  # retry budget exhausted this poll
                self._master_fail_streak += 1
                if (self._master_fail_streak
                        >= spec.master_lost_after_polls):
                    self._master_fail_streak = 0
                    self._handle_master_loss()
                continue
            self._poll_diagnosis_actions()
            # keep the master's donor registry fresh: the worker staged
            # a newer step since the last report (cheap manifest stat)
            self._report_peer_store()
            if waiting > 0:
                logger.info(
                    "%d node(s) waiting: restarting worker to re-form the "
                    "world", waiting,
                )
                obs.get_flight_recorder().record_event(
                    "membership_restart", waiting=waiting)
                self._restart_worker_resilient(count_against_budget=False)

    # -- failure classification / relaunch pacing --------------------------
    def _timeline_step(self) -> int:
        """The job's step high-water mark from the worker's timeline
        export (-1 when absent/corrupt — readers poll mid-flight)."""
        from dlrover_tpu.obs.timeline import load_timeline

        payload = load_timeline(self.timeline_file)
        if payload is None:
            return -1
        steps = (int(s.get("step", -1)) for s in payload["steps"]
                 if isinstance(s, dict))
        return max(steps, default=-1)

    def _handle_worker_failure(self, code: int, kind: str
                               ) -> Optional[int]:
        """One classified worker failure: report it, pace the relaunch
        (backoff + quarantine), restart. Returns a terminal exit code,
        or None when the worker was restarted and the loop continues."""
        spec = self._spec
        recorder = obs.get_flight_recorder()
        lifetime_s = time.monotonic() - self._spawn_ts
        # forward progress = the incarnation pushed the job's step
        # high-water mark; a respawn hanging before it re-reaches the
        # previous mark is exactly the no-progress loop quarantine is
        # for, so re-treading restored steps deliberately doesn't count
        made_progress = self._timeline_step() > self._spawn_step
        recorder.record_event("worker_failed", exit_code=code, kind=kind,
                              restart=self._restart_count)
        if kind == NodeExitReason.HANG:
            recorder.record_event("worker_hang_abort", exit_code=code)
        try:
            self._client.report_failure(
                f"worker exit code {code}",
                level=TrainingMsgLevel.PROCESS_ERROR,
                restart_count=self._restart_count,
                exit_kind=kind,
            )
        except Exception:  # master down: the restart path's own
            # rendezvous will surface a persistent outage
            logger.warning("could not report worker failure "
                           "(master unreachable)")
        # a watchdog hang-abort is the backstop doing its job, not a
        # worker defect: restart without charging max_restarts (parity
        # with the HangingDetector path) — the governor's consecutive
        # no-progress-hang count quarantines a deterministic hang loop
        # the time window alone could never catch
        counts = kind != NodeExitReason.HANG
        if not counts:
            self._governor.record_hang(lifetime_s,
                                       made_progress=made_progress)
        if counts and self._restart_count >= spec.max_restarts:
            logger.error(
                "worker failed (exit %d, %s) with restart budget "
                "exhausted (%d)", code, kind, spec.max_restarts,
            )
            return code
        delay = self._governor.record_failure(
            lifetime_s, made_progress=made_progress)
        registry = obs.get_registry()
        registry.gauge(
            "dlrover_tpu_agent_relaunch_backoff_seconds",
            "Backoff applied before the most recent worker relaunch",
        ).set(delay)
        if self._governor.quarantined:
            registry.gauge(
                "dlrover_tpu_agent_quarantined",
                "1 while this agent's rank is quarantined "
                "(relaunches stopped after repeated failures)").set(1)
            recorder.record_event(
                "worker_quarantined", exit_code=code, kind=kind,
                recent_failures=self._governor.recent_failures)
            logger.error(
                "worker QUARANTINED: %d failures inside the window; "
                "refusing to relaunch (exit %d)",
                self._governor.recent_failures, code)
            try:
                self._client.report_failure(
                    f"rank quarantined after "
                    f"{self._governor.recent_failures} failures",
                    level=TrainingMsgLevel.NODE_ERROR,
                    restart_count=self._restart_count,
                    exit_kind=kind,
                )
            except Exception:  # noqa: BLE001
                pass
            return code
        if delay > 0:
            recorder.record_event("relaunch_backoff", delay_s=delay,
                                  recent_failures=(
                                      self._governor.recent_failures))
            logger.warning(
                "worker failed (exit %d, %s); backing off %.1fs before "
                "relaunch (%d recent failures)", code, kind, delay,
                self._governor.recent_failures)
            self._interruptible_wait(delay)
            if self._shutdown.is_set():
                return 0
            # a preemption notice mid-backoff outranks the relaunch:
            # sleeping through it would eat the grace window and the
            # respawn would die with the VM anyway — drain instead
            if self._preempt_event.is_set():
                logger.warning(
                    "preemption notice during relaunch backoff; "
                    "draining instead of respawning")
                return self._drain(self._preempt_notice)
        logger.warning(
            "worker failed (exit %d, %s); restarting (%d/%d)",
            code, kind, self._restart_count + (1 if counts else 0),
            spec.max_restarts,
        )
        self._restart_worker_resilient(count_against_budget=counts)
        return None

    # -- peer-to-peer restore ----------------------------------------------
    def _start_peer_donor(self) -> None:
        """Serve this host's staged state to replacement ranks. Owned by
        the agent — it must survive the worker restarts every membership
        change forces. Best-effort: with no donor the fleet degrades to
        the Orbax restore path, never to a broken agent."""
        from dlrover_tpu.common.config import Context

        if not Context.singleton().peer_restore_enabled:
            return
        from dlrover_tpu.checkpoint.peer_restore import PeerDonorServer

        try:
            self._peer_donor = PeerDonorServer(self.peer_cache_dir)
            self._peer_donor.start()
        except Exception:  # noqa: BLE001 — port/bind failures vary
            logger.warning("peer donor server failed to start; this "
                           "host will not donate state", exc_info=True)
            self._peer_donor = None

    def _stop_peer_donor(self) -> None:
        if self._peer_donor is not None:
            self._peer_donor.stop()
            self._peer_donor = None

    def _report_peer_store(self, force: bool = False) -> None:
        """Advertise the staged manifest (step + shard keys) to the
        master's donor registry; withdrawn when nothing is staged. Only
        a CHANGED manifest pays for the parse + RPC unless forced (the
        monitor tick's check is one os.stat)."""
        if self._peer_donor is None:
            return
        from dlrover_tpu.checkpoint.peer_restore import (
            MANIFEST,
            manifest_summary,
        )

        try:
            st = os.stat(os.path.join(self.peer_cache_dir, MANIFEST))
            statkey: Optional[Tuple] = (st.st_ino, st.st_mtime_ns,
                                        st.st_size)
        except OSError:
            statkey = None
        if not force and statkey == self._peer_reported_statkey:
            return
        step, keys, total_bytes = manifest_summary(self.peer_cache_dir)
        try:
            self._client.report_peer_store(
                self._peer_donor.addr, step, keys,
                total_bytes=total_bytes, rdzv_name=self._rdzv_name)
            self._peer_reported_statkey = statkey
        except Exception:  # noqa: BLE001 — registry refresh is
            # best-effort; the next tick (or the pre-join force) retries
            logger.warning("could not report peer store to the master")

    def _publish_restore_plan(self) -> None:
        """The restore plan the join result carried → the plan file the
        spawned worker reads (workers with a master client re-fetch a
        fresh plan via RPC; this copy serves the rest and records the
        plan at the re-rendezvous cut)."""
        payload = self._client.last_restore_plan_json or "{}"
        tmp = f"{self.restore_plan_file}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self.restore_plan_file)
        except OSError:
            logger.warning("could not publish the restore plan file")
        # the parallelism plan rides the same join result: the mesh +
        # batch shape the new world agreed on (parallel/planner.py)
        shard_payload = getattr(self._client, "last_shard_plan_json",
                                "") or "{}"
        tmp = f"{self.shard_plan_file}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(shard_payload)
            os.replace(tmp, self.shard_plan_file)
        except OSError:
            logger.warning("could not publish the shard plan file")

    # -- preemption drain --------------------------------------------------
    def _start_preemption_watcher(self) -> None:
        def _on_notice(notice: PreemptionNotice) -> None:
            # watcher thread: only flip the event — the main run loop
            # owns the worker process and every RPC
            self._preempt_notice = notice
            self._preempt_event.set()

        self._preempt_watcher = PreemptionWatcher(
            _on_notice,
            sources=default_sources(notice_file=self.preempt_notice_file))
        self._preempt_watcher.start()

    def _drain(self, notice: PreemptionNotice) -> int:
        """The graceful exit: announce the drain, hand the worker a
        deadline-bounded save-and-exit request, await the clean-drain
        exit (force-stopping at the deadline — the VM dies then anyway),
        conclude with the master. Always a NON-failure: no relaunch
        charge, no failure report."""
        recorder = obs.get_flight_recorder()
        deadline = notice.deadline
        recorder.record_event(
            "preempt_notice", rank=self._client.node_rank,
            deadline=deadline, grace_s=round(notice.grace_s, 1),
            source=notice.source, reason=notice.reason[:256])
        obs.get_registry().counter(
            "dlrover_tpu_agent_preempt_notices_total",
            "Preemption notices this agent acted on",
            labelnames=("source",)).labels(source=notice.source).inc()
        with obs.span("drain", {"rank": self._client.node_rank,
                                "source": notice.source}) as drain_span:
            # the worker's drain request goes out FIRST: against an
            # unreachable master the announce below burns its whole RPC
            # retry budget, and every second of that comes out of the
            # grace window — the emergency checkpoint must already be
            # running by then
            self._drain_seq += 1
            write_drain_request(self.drain_request_file, self._drain_seq,
                                deadline, reason=notice.reason,
                                exit_worker=True)
            try:
                result = self._client.report_drain(
                    deadline, reason=notice.reason, phase="notice")
                logger.info(
                    "drain announced to the master (urgent checkpoint "
                    "fanned out to ranks %s)", result.checkpoint_ranks)
            except Exception:  # noqa: BLE001 — master down: the local
                # emergency checkpoint matters more than the announce
                logger.warning("could not announce drain to the master; "
                               "draining locally anyway")
            code = self._await_worker_departure(deadline)
            drain_span.set_attr("exit_code", code)
        return self._conclude_drain(code, deadline, notice.reason)

    def _await_worker_departure(self, deadline: float) -> int:
        """Poll the worker until it exits or the deadline lands; a
        worker that ignored the drain request (not running the elastic
        loop, or wedged) is force-stopped — better a SIGTERM save than
        the platform's SIGKILL a moment later."""
        while time.time() < deadline:
            if self._shutdown.is_set():
                break
            code = self._proc.poll() if self._proc is not None else 0
            if code is not None:
                return code
            time.sleep(0.2)
        logger.warning("worker still running at the drain deadline; "
                       "force-stopping")
        self._stop_worker()
        return (self._proc.returncode
                if self._proc is not None else 0)

    def _hang_watchdog_enabled(self) -> bool:
        from dlrover_tpu.common.config import Context
        return Context.singleton().hang_watchdog_s > 0

    def _conclude_drain(self, code: int, deadline: float,
                        reason: str) -> int:
        kind = WorkerExit.classify(
            code, hang_enabled=self._hang_watchdog_enabled())
        clean = kind in (NodeExitReason.DRAINED,
                         NodeExitReason.SUCCEEDED)
        obs.get_flight_recorder().record_event(
            "worker_drained", exit_code=code, kind=kind, clean=clean,
            reason=reason[:256])
        try:
            self._client.report_drain(deadline, reason=reason,
                                      phase="complete")
        except Exception:  # noqa: BLE001 — the blown-deadline reap on
            # the master is the fallback when this RPC is lost
            logger.warning("could not report drain completion")
        logger.info("drain complete (worker exit %d, %s): agent "
                    "departing", code, kind)
        return 0 if clean else code

    # -- diagnosis actions -------------------------------------------------
    def _poll_diagnosis_actions(self) -> None:
        """Drain and execute the master's diagnosis actions for this
        rank. Best-effort by contract: a failed poll is just skipped
        (master-loss detection stays the num_nodes_waiting poll's job),
        and an action that cannot execute must not kill the agent."""
        try:
            actions = self._client.poll_diagnosis_actions()
        except Exception:  # noqa: BLE001 — droppable, next tick retries
            return
        for action in actions:
            try:
                self._execute_diagnosis_action(action)
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis action failed: %s", action)

    def _execute_diagnosis_action(self, action: dict) -> None:
        kind = str(action.get("kind", "observe"))
        reason = str(action.get("reason", ""))
        obs.get_flight_recorder().record_event(
            "diagnosis_action_executed", kind=kind,
            id=action.get("id", 0), reason=reason[:256])
        obs.get_registry().counter(
            "dlrover_tpu_agent_diagnosis_actions_total",
            "Diagnosis actions this agent executed",
            labelnames=("kind",)).labels(kind=kind).inc()
        if kind == "profile":
            self._request_profile(action)
        elif kind == "checkpoint":
            self._request_checkpoint(action)
        elif kind == "drain":
            self._request_slice_drain(action)
        elif kind == "restart":
            logger.warning("diagnosis: restarting worker (%s)", reason)
            self._restart_worker_resilient(count_against_budget=False)
        elif kind == "alert":
            logger.warning("diagnosis alert: %s", reason)
        else:
            logger.info("diagnosis observe: %s", reason)

    def _request_profile(self, action: dict) -> None:
        """Round a master `profile:{rank}` action into an actual capture:
        publish a request the worker's ProfilerSession polls each step
        (obs/profiler.py); the capture artifact (trace dir + manifest)
        lands under the agent workdir."""
        self._profile_request_seq += 1
        num_steps = int(action.get("num_steps", 5) or 5)
        obs.write_profile_request(
            self.profile_request_file, self._profile_request_seq,
            num_steps, self.profile_dump_dir)
        logger.info(
            "diagnosis: requested a %d-step profiler capture (#%d) -> %s",
            num_steps, self._profile_request_seq, self.profile_dump_dir)

    def _request_checkpoint(self, action: dict) -> None:
        """A master `checkpoint:{rank}` action (a peer is draining):
        hand the worker a save-now-KEEP-RUNNING request through the
        drain file — the step loop saves at its next boundary."""
        from dlrover_tpu.common.config import Context

        self._drain_seq += 1
        deadline = float(action.get("deadline", 0.0) or 0.0)
        if deadline <= 0.0:
            deadline = (time.time()
                        + Context.singleton().preempt_default_grace_s)
        write_drain_request(
            self.drain_request_file, self._drain_seq, deadline,
            reason=str(action.get("reason", "")), exit_worker=False)
        logger.info(
            "diagnosis: urgent checkpoint requested of the worker "
            "(#%d, deadline in %.0fs)", self._drain_seq,
            max(0.0, deadline - time.time()))

    def _request_slice_drain(self, action: dict) -> None:
        """A master ``drain:{rank}`` action (this rank's SLICE is
        draining — some peer in it got the preemption notice): hand the
        worker a save-and-EXIT request. The worker departs with the
        clean-drain code, the run loop classifies it DRAINED and
        concludes the drain with the master — the whole slice leaves as
        one unit, no liveness-timeout stragglers."""
        from dlrover_tpu.common.config import Context

        self._drain_seq += 1
        deadline = float(action.get("deadline", 0.0) or 0.0)
        if deadline <= 0.0:
            deadline = (time.time()
                        + Context.singleton().preempt_default_grace_s)
        write_drain_request(
            self.drain_request_file, self._drain_seq, deadline,
            reason=str(action.get("reason", "")), exit_worker=True)
        logger.warning(
            "slice drain requested of the worker (#%d, deadline in "
            "%.0fs): %s", self._drain_seq,
            max(0.0, deadline - time.time()),
            str(action.get("reason", ""))[:256])

    # -- master failover ---------------------------------------------------
    def _handle_master_loss(self) -> None:
        """Degraded "master lost" mode. The worker keeps training — it
        only needs the master for shards and elasticity — while this
        loop (1) re-resolves the master address (bootstrap file / env),
        (2) reconnects with jittered exponential backoff, (3)
        re-registers through the generation-token handshake, and (4)
        re-syncs rendezvous state, restarting the worker only when the
        world actually moved on. Raises MasterLostError once
        master_reconnect_timeout_s is exhausted."""
        from dlrover_tpu.common.config import Context

        ctx = Context.singleton()
        recorder = obs.get_flight_recorder()
        logger.error(
            "master at %s unreachable: entering master-lost mode "
            "(worker keeps running; reconnect budget %.0fs)",
            self._client.master_addr, ctx.master_reconnect_timeout_s)
        recorder.record_event("master_lost",
                              addr=self._client.master_addr,
                              rank=self._client.node_rank)
        obs.get_registry().counter(
            "dlrover_tpu_master_lost_total",
            "Master-lost episodes entered by this agent").inc()
        while True:
            try:
                result = self._reconnect_master(ctx, recorder)
            except PreemptedDuringOutage:
                # this host is going away: every second spent dialing
                # the dead master comes out of the emergency-checkpoint
                # window. Return to the run loop, whose next tick
                # consumes the preempt event and drains locally (the
                # drain path already tolerates an unreachable master).
                logger.warning(
                    "preemption notice during master-lost reconnect; "
                    "abandoning the reconnect to drain locally")
                return
            try:
                self._resync_rendezvous(result)
                return
            except grpc.RpcError as exc:
                # the master flapped again mid-resync: back to the
                # reconnect loop (each successful reconnect earned a
                # fresh budget — progress was made) rather than dying
                # on one RPC retry budget. Anything non-transport
                # (RendezvousTimeoutError, a spawn OSError) propagates —
                # retrying those against a healthy master loops forever.
                logger.warning(
                    "master flapped during rendezvous re-sync (%s); "
                    "re-entering the reconnect loop", exc)

    def _reconnect_master(self, ctx, recorder):
        """Dial until one reconnect_report round-trips (or the budget
        runs out); returns the master's ReconnectResult."""
        deadline = time.time() + ctx.master_reconnect_timeout_s
        attempt = 0
        while True:
            if self._shutdown.is_set():
                raise MasterLostError("agent shut down mid-reconnect")
            if self._preempt_event.is_set():
                raise PreemptedDuringOutage()
            addr = self._client.resolve_master_addr(
                self._client.master_addr)
            try:
                with obs.span("reconnect",
                              {"addr": addr,
                               "rank": self._client.node_rank,
                               "attempt": attempt}) as reconnect_span:
                    self._client.reconnect(addr)
                    result = self._client.reconnect_report(
                        local_world_size=self._spec.devices_per_node,
                        rdzv_name=self._rdzv_name,
                        rdzv_round=self.last_round,
                    )
                    reconnect_span.set_attr("generation",
                                            result.generation)
                    reconnect_span.set_attr("world_intact",
                                            result.world_intact)
            except Exception as exc:  # noqa: BLE001 — grpc errors vary
                attempt += 1
                if time.time() >= deadline:
                    raise MasterLostError(
                        f"master unreachable for "
                        f"{ctx.master_reconnect_timeout_s:.0f}s "
                        f"(last tried {addr})") from exc
                delay = backoff_delay_s(attempt, ctx.rpc_backoff_s,
                                        ctx.rpc_backoff_max_s)
                logger.warning(
                    "master still unreachable at %s (attempt %d): %s; "
                    "next dial in %.1fs", addr, attempt, exc, delay)
                # a preemption notice (or a shutdown) mid-sleep must
                # not wait out the full delay — the grace window is
                # shorter than rpc_backoff_max_s
                self._interruptible_wait(delay)
                continue
            logger.info(
                "reconnected to master %s (generation %d, world "
                "intact=%s)", addr, result.generation,
                result.world_intact)
            recorder.record_event(
                "master_reconnected", addr=addr,
                generation=result.generation,
                world_intact=result.world_intact)
            return result

    def _resync_rendezvous(self, result) -> None:
        """After re-registration: keep the running worker only when the
        restored master still holds OUR world as its latest; otherwise
        restart so the world re-forms through a fresh rendezvous."""
        with obs.span("rendezvous",
                      {"rdzv": self._rdzv_name,
                       "rank": self._client.node_rank,
                       "resync": True}) as resync_span:
            worker_alive = (self._proc is not None
                            and self._proc.poll() is None)
            intact = result.world_intact and worker_alive
            if intact:
                try:
                    _, _, world = self._client.get_comm_world(
                        self._rdzv_name)
                    intact = bool(world) and world == self.last_world
                except Exception:  # noqa: BLE001 — master flapped again
                    intact = False
            resync_span.set_attr("world_intact", intact)
            if intact:
                logger.info("world %s survived the master outage; "
                            "worker keeps running", sorted(self.last_world))
                return
            logger.info("world changed across the master outage; "
                        "restarting worker to re-form")
            self._restart_worker(count_against_budget=False)

    def shutdown(self) -> None:
        self._shutdown.set()
        self._stop_monitors()
        if self._preempt_watcher is not None:
            self._preempt_watcher.stop()
        self._stop_worker()
        self._stop_peer_donor()
        obs.remove_span_sink(self._span_exporter)


def apply_jax_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` explicitly in worker processes.

    Platform plugins registered from site hooks can prepend themselves to
    ``jax_platforms`` regardless of the env var, so a worker the agent
    intended to run on a specific platform (e.g. tests forcing ``cpu``)
    must re-assert it through jax.config before backend init."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def init_distributed() -> None:
    """Training-process entry: initialize jax.distributed from the agent's
    env contract. No-op single-process (standalone runs)."""
    apply_jax_platform_env()
    world_size = int(os.getenv(NodeEnv.WORLD_SIZE, "1"))
    if world_size <= 1:
        return
    import jax

    # Default 300 s coordinator-registration deadline is too tight when
    # several probe/worker processes cold-compile on a loaded shared host
    # (observed: DEADLINE_EXCEEDED on CoordinationService/RegisterTask) —
    # give registration the same generous budget the agent gives compiles.
    init_timeout = int(os.getenv("DLROVER_TPU_DIST_INIT_TIMEOUT", "600"))
    jax.distributed.initialize(
        coordinator_address=os.environ[NodeEnv.COORDINATOR_ADDR],
        num_processes=world_size,
        process_id=int(os.environ[NodeEnv.PROCESS_ID]),
        initialization_timeout=init_timeout,
    )
