"""Node-side elastic agent: rendezvous, spawn, monitor, restart.

Capability parity: dlrover/python/elastic_agent/torch/training.py —
``ElasticTrainingAgent`` (rendezvous :315, monitor/restart loop :429-521,
failure reporting :490) re-designed for JAX workers:

- One agent per TPU host. The worker it spawns is ONE JAX process that owns
  all local chips (torch spawns one proc per GPU; JAX is one proc per host).
- Rendezvous yields {node_rank → local chip count}; the agent derives
  ``jax.distributed`` (num_processes, process_id) and the round's coordinator
  address, published through the master KV store (replacing the reference's
  MasterKVStore/c10d bootstrap, elastic_agent/torch/master_kv_store.py).
- On worker failure: report to master, re-rendezvous, respawn (restart
  budget). On membership change (``num_nodes_waiting > 0``): graceful
  restart so the world re-forms — training re-lowers to the new mesh.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import grpc

from dlrover_tpu import obs
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.bootstrap import publish_or_wait_coordinator
from dlrover_tpu.common.constants import (
    DefaultValues,
    NodeEnv,
    RendezvousName,
    TrainingMsgLevel,
)
from dlrover_tpu.common.log import default_logger as logger


@dataclasses.dataclass
class WorkerSpec:
    """What to run on this node."""

    entrypoint: List[str]                    # argv of the training process
    devices_per_node: int = 1                # local chip count
    max_restarts: int = DefaultValues.MAX_RELAUNCH
    monitor_interval_s: float = DefaultValues.MONITOR_INTERVAL_S
    rdzv_timeout_s: float = DefaultValues.RDZV_TIMEOUT_S
    # SIGTERM → SIGKILL grace: must cover one train step + a forced
    # checkpoint commit (the worker saves on SIGTERM, elastic_loop.py).
    shutdown_grace_s: float = 120.0
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    # side monitors (resource/step reporting, tuned-config polling)
    enable_monitors: bool = True
    # restart the worker when step progress stalls (atorch
    # --relaunch_on_hanging analog)
    relaunch_on_hanging: bool = False
    # consecutive failed num_nodes_waiting polls (each already a full
    # retry_rpc budget) before declaring the master lost and entering
    # the degraded reconnect loop
    master_lost_after_polls: int = 2

    def __post_init__(self) -> None:
        # THIS interval (not Context.monitor_interval_s, an independent
        # master-side knob) paces the agent's num_nodes_waiting poll —
        # the master's main liveness signal. A dead-node timeout under
        # ~3 polls reaps healthy agents that merely missed one tick.
        from dlrover_tpu.common.config import Context

        timeout = Context.singleton().dead_node_timeout_s
        if 0 < timeout < 3 * self.monitor_interval_s:
            logger.warning(
                "dead_node_timeout_s (%.0fs) < 3x the agent poll "
                "interval (--monitor-interval %.0fs): healthy agents "
                "may be declared dead between polls; raise the timeout "
                "or lower the poll interval",
                timeout, self.monitor_interval_s)


class RendezvousTimeoutError(TimeoutError):
    pass


class MasterLostError(RuntimeError):
    """The master stayed unreachable past the reconnect budget."""


class ElasticAgent:
    """Joins the master rendezvous and keeps one training process alive."""

    def __init__(self, client: MasterClient, spec: WorkerSpec,
                 rdzv_name: str = RendezvousName.TRAINING):
        self._client = client
        self._spec = spec
        self._rdzv_name = rdzv_name
        self._restart_count = 0
        self._master_fail_streak = 0
        # set by shutdown(): the run loop must not resurrect the worker
        # it just killed, and reconnect loops must stop dialing
        self._shutdown = threading.Event()
        self._proc: Optional[subprocess.Popen] = None
        self.last_world: Dict[int, int] = {}
        self.last_round = -1
        self._monitors: List = []
        self._hang_detector = None
        # set by the HangingDetector thread; consumed (and acted on) only
        # by the main run() loop so worker restarts never race
        self._hang_event = threading.Event()
        self._workdir = tempfile.mkdtemp(prefix="dlrover-tpu-agent-")
        self.metrics_file = os.path.join(self._workdir, "metrics.jsonl")
        self.chip_stats_file = os.path.join(self._workdir, "chips.json")
        self.paral_config_file = os.path.join(self._workdir, "paral.json")
        # diagnosis plumbing: the worker exports its per-step phase
        # timeline here, and picks up on-demand profiler captures the
        # agent requests when executing a master `profile:{rank}` action
        self.timeline_file = os.path.join(self._workdir, "timeline.json")
        self.profile_request_file = os.path.join(
            self._workdir, "profile_request.json")
        self.profile_dump_dir = os.path.join(self._workdir, "profiles")
        self._profile_request_seq = 0
        # Persistent XLA compile cache shared across worker restarts: an
        # elastic restart re-lowers the same programs, so the respawned
        # worker skips compilation — the dominant cost of a fast restore.
        self.compile_cache_dir = os.path.join(self._workdir, "xla-cache")
        # batches the agent's finished spans (rendezvous etc.) for the
        # master's job-wide timeline; flushed from the monitor loop
        self._span_exporter = obs.SpanExporter()
        obs.add_span_sink(self._span_exporter)

    # -- rendezvous --------------------------------------------------------
    def rendezvous(self) -> Tuple[int, Dict[int, int]]:
        """Join and poll until this node is in a completed world
        (reference: MasterRendezvousHandler.next_rendezvous training.py:180).
        """
        spec = self._spec
        # the agent-side rendezvous span is the trace root: the join RPC
        # carries its context, so the master's rendezvous_join span (and
        # everything the master hangs beneath it) shares this trace
        with obs.span("rendezvous",
                      {"rdzv": self._rdzv_name,
                       "rank": self._client.node_rank}) as rdzv_span:
            joined_round = self._client.join_rendezvous(
                spec.devices_per_node, self._rdzv_name)
            deadline = time.time() + spec.rdzv_timeout_s
            while time.time() < deadline:
                rdzv_round, _, world = self._client.get_comm_world(
                    self._rdzv_name
                )
                if world and self._client.node_rank in world:
                    self.last_world, self.last_round = world, rdzv_round
                    rdzv_span.set_attr("round", rdzv_round)
                    rdzv_span.set_attr("world_size", len(world))
                    return rdzv_round, world
                if rdzv_round > joined_round:
                    # Our round was cut without us — the world was
                    # invalidated by a member death, or node_unit rounding
                    # dropped us. Re-join so the next round can include
                    # this node.
                    logger.info(
                        "rendezvous round %d passed without this node; "
                        "re-joining", joined_round,
                    )
                    joined_round = self._client.join_rendezvous(
                        spec.devices_per_node, self._rdzv_name)
                time.sleep(0.5)
            raise RendezvousTimeoutError(
                f"rendezvous {self._rdzv_name!r} did not complete within "
                f"{spec.rdzv_timeout_s:.0f}s"
            )

    def _bootstrap_env(self, rdzv_round: int,
                       world: Dict[int, int]) -> Dict[str, str]:
        """Derive the JAX process set for this round; the lowest rank
        publishes the coordinator address via the master KV store."""
        ranks = sorted(world)
        process_id = ranks.index(self._client.node_rank)
        coord = publish_or_wait_coordinator(
            self._client, f"coord/{self._rdzv_name}/{rdzv_round}",
            process_id, self._spec.rdzv_timeout_s,
        )
        env = dict(os.environ)
        env.update(self._spec.env)
        env.update({
            NodeEnv.MASTER_ADDR: self._client.master_addr,
            NodeEnv.NODE_ID: str(self._client.node_id),
            NodeEnv.NODE_RANK: str(self._client.node_rank),
            NodeEnv.WORLD_SIZE: str(len(ranks)),
            NodeEnv.PROCESS_ID: str(process_id),
            NodeEnv.COORDINATOR_ADDR: coord,
            NodeEnv.RDZV_ROUND: str(rdzv_round),
            NodeEnv.DEVICES_PER_NODE: str(self._spec.devices_per_node),
            NodeEnv.METRICS_FILE: self.metrics_file,
            NodeEnv.CHIP_STATS_FILE: self.chip_stats_file,
            NodeEnv.PARAL_CONFIG_PATH: self.paral_config_file,
            NodeEnv.TIMELINE_FILE: self.timeline_file,
            NodeEnv.PROFILE_REQUEST_FILE: self.profile_request_file,
        })
        env.setdefault("JAX_COMPILATION_CACHE_DIR", self.compile_cache_dir)
        return env

    # -- worker lifecycle --------------------------------------------------
    def _spawn(self) -> None:
        rdzv_round, world = self.rendezvous()
        env = self._bootstrap_env(rdzv_round, world)
        logger.info(
            "spawning worker (round %d, world %s, restart %d): %s",
            rdzv_round, sorted(world), self._restart_count,
            self._spec.entrypoint,
        )
        self._proc = subprocess.Popen(self._spec.entrypoint, env=env)
        obs.get_flight_recorder().record_event(
            "worker_spawn", round=rdzv_round, world=sorted(world),
            restart=self._restart_count, pid=self._proc.pid)

    def _stop_worker(self) -> None:
        if self._proc is None or self._proc.poll() is not None:
            return
        self._proc.send_signal(signal.SIGTERM)
        try:
            self._proc.wait(self._spec.shutdown_grace_s)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()

    def _restart_worker_resilient(self, count_against_budget: bool
                                  ) -> None:
        """_restart_worker, but a restart whose own rendezvous cannot
        reach the master falls into master-lost handling: a worker crash
        DURING a master outage gets the full reconnect budget
        (master_reconnect_timeout_s), not just one RPC retry budget.
        After reconnection the resync sees the dead worker and respawns
        it. ONLY transport errors divert: a RendezvousTimeoutError
        (master answered, world never formed) or a spawn failure
        (Popen OSError — the entrypoint itself is broken, and retrying
        against a healthy master would loop forever) propagates."""
        try:
            self._restart_worker(count_against_budget)
        except grpc.RpcError as exc:
            logger.warning(
                "worker restart could not reach the master (%s); "
                "entering master-lost mode", exc)
            self._handle_master_loss()

    def _restart_worker(self, count_against_budget: bool) -> None:
        """Membership-change restarts are normal elasticity and do NOT
        consume the failure budget (reference: torchelastic only charges
        the budget on the failure path)."""
        self._stop_worker()
        if count_against_budget:
            self._restart_count += 1
        self._spawn()
        self._hang_event.clear()  # a stale flag must not re-kill the
        # fresh worker (e.g. hang flagged, then crash-path restarted)
        if self._hang_detector is not None:
            self._hang_detector.reset()  # fresh compile grace period

    def _start_monitors(self) -> None:
        if not self._spec.enable_monitors:
            return
        from dlrover_tpu.agent.monitor import (
            HangingDetector,
            ParalConfigTuner,
            ResourceMonitor,
            TrainingMonitor,
        )

        self._monitors = [
            ResourceMonitor(self._client,
                            chip_stats_file=self.chip_stats_file),
            TrainingMonitor(self._client, self.metrics_file),
            ParalConfigTuner(self._client, self.paral_config_file),
        ]
        if self._spec.relaunch_on_hanging:
            self._hang_detector = HangingDetector(
                self.metrics_file,
                on_hang=self._hang_event.set,
            )
            self._monitors.append(self._hang_detector)
        for monitor in self._monitors:
            monitor.start()

    def _stop_monitors(self) -> None:
        for monitor in self._monitors:
            monitor.stop()
        self._monitors = []

    # -- main loop ---------------------------------------------------------
    def run(self) -> int:
        """Monitor loop (reference: _invoke_run training.py:429-521).
        Returns the worker's final exit code."""
        recorder = obs.get_flight_recorder()
        if threading.current_thread() is threading.main_thread():
            # postmortem timeline even when the platform SIGTERMs the
            # agent itself (signal API is main-thread-only)
            recorder.install_signal_handlers()
        recorder.install_excepthook()
        self._spawn()
        self._start_monitors()
        try:
            return self._run_loop()
        except BaseException:
            # master-lost (and only master-lost) paths can raise with a
            # LIVE worker — never orphan the trainer on the way out
            self._stop_worker()
            raise
        finally:
            self._stop_monitors()
            self._flush_telemetry()
            obs.remove_span_sink(self._span_exporter)
            recorder.dump(reason="agent-exit")

    def _flush_telemetry(self) -> None:
        self._span_exporter.flush_to(self._client)

    def _run_loop(self) -> int:
        spec = self._spec
        while True:
            time.sleep(spec.monitor_interval_s)
            if self._shutdown.is_set():
                return 0
            self._flush_telemetry()
            code = self._proc.poll()
            if code is not None:
                if self._shutdown.is_set():
                    return 0
                if code == 0:
                    logger.info("worker finished successfully")
                    return 0
                obs.get_flight_recorder().record_event(
                    "worker_failed", exit_code=code,
                    restart=self._restart_count)
                try:
                    self._client.report_failure(
                        f"worker exit code {code}",
                        level=TrainingMsgLevel.PROCESS_ERROR,
                        restart_count=self._restart_count,
                    )
                except Exception:  # master down: the restart path's own
                    # rendezvous will surface a persistent outage
                    logger.warning("could not report worker failure "
                                   "(master unreachable)")
                if self._restart_count >= spec.max_restarts:
                    logger.error(
                        "worker failed (exit %d) with restart budget "
                        "exhausted (%d)", code, spec.max_restarts,
                    )
                    return code
                logger.warning(
                    "worker failed (exit %d); restarting (%d/%d)",
                    code, self._restart_count + 1, spec.max_restarts,
                )
                self._restart_worker_resilient(count_against_budget=True)
                continue
            # Hang flagged by the detector thread: restart HERE so only
            # the main loop ever touches the worker process.
            if self._hang_event.is_set():
                self._hang_event.clear()
                logger.error("restarting hanged worker")
                obs.get_flight_recorder().record_event("worker_hang")
                self._restart_worker_resilient(count_against_budget=False)
                continue
            # Healthy: check membership first, then execute any
            # diagnosis actions the master queued for this rank
            # (reference: training.py:483-486,510-521). Actions are
            # polled only after a SUCCESSFUL liveness probe: during a
            # master outage an extra un-retried RPC here would block a
            # full timeout per tick before the probe that actually
            # advances the master-lost streak.
            try:
                waiting = self._client.num_nodes_waiting(self._rdzv_name)
                self._master_fail_streak = 0
            except Exception:  # retry budget exhausted this poll
                self._master_fail_streak += 1
                if (self._master_fail_streak
                        >= spec.master_lost_after_polls):
                    self._master_fail_streak = 0
                    self._handle_master_loss()
                continue
            self._poll_diagnosis_actions()
            if waiting > 0:
                logger.info(
                    "%d node(s) waiting: restarting worker to re-form the "
                    "world", waiting,
                )
                obs.get_flight_recorder().record_event(
                    "membership_restart", waiting=waiting)
                self._restart_worker_resilient(count_against_budget=False)

    # -- diagnosis actions -------------------------------------------------
    def _poll_diagnosis_actions(self) -> None:
        """Drain and execute the master's diagnosis actions for this
        rank. Best-effort by contract: a failed poll is just skipped
        (master-loss detection stays the num_nodes_waiting poll's job),
        and an action that cannot execute must not kill the agent."""
        try:
            actions = self._client.poll_diagnosis_actions()
        except Exception:  # noqa: BLE001 — droppable, next tick retries
            return
        for action in actions:
            try:
                self._execute_diagnosis_action(action)
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis action failed: %s", action)

    def _execute_diagnosis_action(self, action: dict) -> None:
        kind = str(action.get("kind", "observe"))
        reason = str(action.get("reason", ""))
        obs.get_flight_recorder().record_event(
            "diagnosis_action_executed", kind=kind,
            id=action.get("id", 0), reason=reason[:256])
        obs.get_registry().counter(
            "dlrover_tpu_agent_diagnosis_actions_total",
            "Diagnosis actions this agent executed",
            labelnames=("kind",)).labels(kind=kind).inc()
        if kind == "profile":
            self._request_profile(action)
        elif kind == "restart":
            logger.warning("diagnosis: restarting worker (%s)", reason)
            self._restart_worker_resilient(count_against_budget=False)
        elif kind == "alert":
            logger.warning("diagnosis alert: %s", reason)
        else:
            logger.info("diagnosis observe: %s", reason)

    def _request_profile(self, action: dict) -> None:
        """Round a master `profile:{rank}` action into an actual capture:
        publish a request the worker's ProfilerSession polls each step
        (obs/profiler.py); the capture artifact (trace dir + manifest)
        lands under the agent workdir."""
        self._profile_request_seq += 1
        num_steps = int(action.get("num_steps", 5) or 5)
        obs.write_profile_request(
            self.profile_request_file, self._profile_request_seq,
            num_steps, self.profile_dump_dir)
        logger.info(
            "diagnosis: requested a %d-step profiler capture (#%d) -> %s",
            num_steps, self._profile_request_seq, self.profile_dump_dir)

    # -- master failover ---------------------------------------------------
    def _handle_master_loss(self) -> None:
        """Degraded "master lost" mode. The worker keeps training — it
        only needs the master for shards and elasticity — while this
        loop (1) re-resolves the master address (bootstrap file / env),
        (2) reconnects with jittered exponential backoff, (3)
        re-registers through the generation-token handshake, and (4)
        re-syncs rendezvous state, restarting the worker only when the
        world actually moved on. Raises MasterLostError once
        master_reconnect_timeout_s is exhausted."""
        from dlrover_tpu.agent.master_client import backoff_delay_s
        from dlrover_tpu.common.config import Context

        ctx = Context.singleton()
        recorder = obs.get_flight_recorder()
        logger.error(
            "master at %s unreachable: entering master-lost mode "
            "(worker keeps running; reconnect budget %.0fs)",
            self._client.master_addr, ctx.master_reconnect_timeout_s)
        recorder.record_event("master_lost",
                              addr=self._client.master_addr,
                              rank=self._client.node_rank)
        obs.get_registry().counter(
            "dlrover_tpu_master_lost_total",
            "Master-lost episodes entered by this agent").inc()
        while True:
            result = self._reconnect_master(ctx, recorder)
            try:
                self._resync_rendezvous(result)
                return
            except grpc.RpcError as exc:
                # the master flapped again mid-resync: back to the
                # reconnect loop (each successful reconnect earned a
                # fresh budget — progress was made) rather than dying
                # on one RPC retry budget. Anything non-transport
                # (RendezvousTimeoutError, a spawn OSError) propagates —
                # retrying those against a healthy master loops forever.
                logger.warning(
                    "master flapped during rendezvous re-sync (%s); "
                    "re-entering the reconnect loop", exc)

    def _reconnect_master(self, ctx, recorder):
        """Dial until one reconnect_report round-trips (or the budget
        runs out); returns the master's ReconnectResult."""
        deadline = time.time() + ctx.master_reconnect_timeout_s
        attempt = 0
        while True:
            if self._shutdown.is_set():
                raise MasterLostError("agent shut down mid-reconnect")
            addr = self._client.resolve_master_addr(
                self._client.master_addr)
            try:
                with obs.span("reconnect",
                              {"addr": addr,
                               "rank": self._client.node_rank,
                               "attempt": attempt}) as reconnect_span:
                    self._client.reconnect(addr)
                    result = self._client.reconnect_report(
                        local_world_size=self._spec.devices_per_node,
                        rdzv_name=self._rdzv_name,
                        rdzv_round=self.last_round,
                    )
                    reconnect_span.set_attr("generation",
                                            result.generation)
                    reconnect_span.set_attr("world_intact",
                                            result.world_intact)
            except Exception as exc:  # noqa: BLE001 — grpc errors vary
                attempt += 1
                if time.time() >= deadline:
                    raise MasterLostError(
                        f"master unreachable for "
                        f"{ctx.master_reconnect_timeout_s:.0f}s "
                        f"(last tried {addr})") from exc
                delay = backoff_delay_s(attempt, ctx.rpc_backoff_s,
                                        ctx.rpc_backoff_max_s)
                logger.warning(
                    "master still unreachable at %s (attempt %d): %s; "
                    "next dial in %.1fs", addr, attempt, exc, delay)
                time.sleep(delay)
                continue
            logger.info(
                "reconnected to master %s (generation %d, world "
                "intact=%s)", addr, result.generation,
                result.world_intact)
            recorder.record_event(
                "master_reconnected", addr=addr,
                generation=result.generation,
                world_intact=result.world_intact)
            return result

    def _resync_rendezvous(self, result) -> None:
        """After re-registration: keep the running worker only when the
        restored master still holds OUR world as its latest; otherwise
        restart so the world re-forms through a fresh rendezvous."""
        with obs.span("rendezvous",
                      {"rdzv": self._rdzv_name,
                       "rank": self._client.node_rank,
                       "resync": True}) as resync_span:
            worker_alive = (self._proc is not None
                            and self._proc.poll() is None)
            intact = result.world_intact and worker_alive
            if intact:
                try:
                    _, _, world = self._client.get_comm_world(
                        self._rdzv_name)
                    intact = bool(world) and world == self.last_world
                except Exception:  # noqa: BLE001 — master flapped again
                    intact = False
            resync_span.set_attr("world_intact", intact)
            if intact:
                logger.info("world %s survived the master outage; "
                            "worker keeps running", sorted(self.last_world))
                return
            logger.info("world changed across the master outage; "
                        "restarting worker to re-form")
            self._restart_worker(count_against_budget=False)

    def shutdown(self) -> None:
        self._shutdown.set()
        self._stop_monitors()
        self._stop_worker()
        obs.remove_span_sink(self._span_exporter)


def apply_jax_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` explicitly in worker processes.

    Platform plugins registered from site hooks can prepend themselves to
    ``jax_platforms`` regardless of the env var, so a worker the agent
    intended to run on a specific platform (e.g. tests forcing ``cpu``)
    must re-assert it through jax.config before backend init."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def init_distributed() -> None:
    """Training-process entry: initialize jax.distributed from the agent's
    env contract. No-op single-process (standalone runs)."""
    apply_jax_platform_env()
    world_size = int(os.getenv(NodeEnv.WORLD_SIZE, "1"))
    if world_size <= 1:
        return
    import jax

    # Default 300 s coordinator-registration deadline is too tight when
    # several probe/worker processes cold-compile on a loaded shared host
    # (observed: DEADLINE_EXCEEDED on CoordinationService/RegisterTask) —
    # give registration the same generous budget the agent gives compiles.
    init_timeout = int(os.getenv("DLROVER_TPU_DIST_INIT_TIMEOUT", "600"))
    jax.distributed.initialize(
        coordinator_address=os.environ[NodeEnv.COORDINATOR_ADDR],
        num_processes=world_size,
        process_id=int(os.environ[NodeEnv.PROCESS_ID]),
        initialization_timeout=init_timeout,
    )
