"""MasterClient: the sole channel from a node to the master.

Capability parity: dlrover/python/elastic_agent/master_client.py:49 — typed
wrappers over the 2-RPC service for every protocol interaction, with a retry
decorator, plus the singleton builder that reads the master address from the
env contract.
"""

from __future__ import annotations

import functools
import os
import random
import time
from typing import Dict, Optional, Tuple

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import (
    MasterStub,
    TransportFaultInjector,
    build_channel,
    local_ip,
)
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import (
    HOT_KV_PREFIXES,
    NodeEnv,
    RendezvousName,
)


def backoff_delay_s(attempt: int, base_s: float, cap_s: float) -> float:
    """Jittered exponential backoff: min(cap, base·2^attempt) scaled by
    a uniform [0.5, 1.0) jitter so a fleet of agents retrying against a
    restarted master doesn't stampede it in lockstep."""
    # exponent clamped: an unbounded attempt counter (a long reconnect
    # loop) must saturate at the cap, not overflow 2.0**1024
    envelope = min(cap_s, base_s * (2.0 ** min(attempt, 62)))
    return envelope * random.uniform(0.5, 1.0)


def retry_rpc(retries: Optional[int] = None,
              backoff_s: Optional[float] = None,
              max_backoff_s: Optional[float] = None):
    """Retry decorator. None parameters resolve from Context at CALL
    time (not import time), so tests — and the agent's master-lost
    handling — can shrink the budget on a live process."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            ctx = Context.singleton()
            attempts = retries if retries is not None else ctx.rpc_retries
            base = backoff_s if backoff_s is not None else ctx.rpc_backoff_s
            cap = (max_backoff_s if max_backoff_s is not None
                   else ctx.rpc_backoff_max_s)
            last_exc = None
            for attempt in range(max(1, attempts)):
                try:
                    return fn(*args, **kwargs)
                except Exception as exc:  # noqa: BLE001 — grpc errors vary
                    last_exc = exc
                    if attempt < attempts - 1:
                        time.sleep(backoff_delay_s(attempt, base, cap))
            raise last_exc

        return wrapped

    return decorator


class MasterClient:
    _singleton: Optional["MasterClient"] = None

    def __init__(self, master_addr: str, node_id: int = 0,
                 node_rank: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 node_type: str = "",
                 slice_id: Optional[int] = None,
                 coord_addr: Optional[str] = None):
        self.master_addr = master_addr
        self.node_id = node_id
        self.node_type = node_type
        self.node_rank = node_rank if node_rank is not None else node_id
        # ICI slice this node belongs to (multi-slice hierarchical DP);
        # -1 = single-slice job. Explicit param wins over the env so
        # in-process tests can run several slices in one process.
        self.slice_id = (slice_id if slice_id is not None
                         else int(os.getenv(NodeEnv.SLICE_ID, "-1")))
        # per-call deadline; wait_for_ready means an unreachable master
        # surfaces as DEADLINE_EXCEEDED after exactly this long
        self._timeout_s = (timeout_s if timeout_s is not None
                           else Context.singleton().rpc_timeout_s)
        # the latest master generation any RPC reported (0 = unknown);
        # presented on reconnect so a restarted master can tell this
        # re-registration from a brand-new joiner
        self.master_generation = 0
        # the peer-restore plan the last join result carried ("" = none):
        # the agent publishes it to the worker via the plan file
        self.last_restore_plan_json = ""
        self.last_shard_plan_json = ""
        # owned by the CLIENT, not the stub: a seeded chaos injector
        # must keep its RNG sequence across reconnect()s, or a seed
        # whose first draw fires would deterministically kill the first
        # RPC after every re-dial
        self._fault_injector = TransportFaultInjector.from_env()
        self._channel = build_channel(master_addr)
        self._stub = MasterStub(self._channel,
                                fault_injector=self._fault_injector)
        # the coordination tier (master/coord_service.py): hot-prefix
        # KV traffic (dcn/ gradient exchange, coord/ barriers) dials
        # this address so it can never queue behind control-tier storms.
        # "" = single-tier master; learned from the env, join results,
        # or the bootstrap file.
        self.coord_addr = ""
        self._coord_channel = None
        self._coord_stub = None
        # breaker: after a coord-tier transport failure, hot traffic
        # goes straight to the main tier until this deadline instead of
        # paying a full RPC timeout per call against a dead tier
        self._coord_down_until = 0.0
        self.set_coord_addr(
            coord_addr if coord_addr is not None
            else os.getenv(NodeEnv.COORD_ADDR, ""))

    def set_coord_addr(self, coord_addr: str) -> None:
        """(Re)dial the coordination tier; "" tears it down (hot traffic
        falls back to the main channel)."""
        if coord_addr == self.coord_addr and (
                bool(coord_addr) == (self._coord_stub is not None)):
            return
        old = self._coord_channel
        self.coord_addr = coord_addr or ""
        self._coord_down_until = 0.0   # a fresh dial resets the breaker
        if coord_addr:
            self._coord_channel = build_channel(coord_addr)
            self._coord_stub = MasterStub(
                self._coord_channel,
                fault_injector=self._fault_injector)
        else:
            self._coord_channel = None
            self._coord_stub = None
        if old is not None:
            try:
                old.close()
            except Exception:  # noqa: BLE001 — a dead channel may refuse
                pass

    def reconnect(self, master_addr: Optional[str] = None,
                  coord_addr: Optional[str] = None) -> None:
        """Tear down the channel and dial (a possibly different) master.
        Existing typed wrappers keep working — they go through the new
        stub on the next call. The coordination tier is re-resolved
        too: a promoted standby binds a fresh coord port."""
        addr = master_addr or self.master_addr
        try:
            self._channel.close()
        except Exception:  # noqa: BLE001 — a dead channel may refuse
            pass
        self.master_addr = addr
        self._channel = build_channel(addr)
        self._stub = MasterStub(self._channel,
                                fault_injector=self._fault_injector)
        if coord_addr is not None:
            self.set_coord_addr(coord_addr)

    @staticmethod
    def resolve_bootstrap() -> dict:
        """The bootstrap file's parsed contents: {"addr", "coord_addr",
        "generation"} — JSON since the hot-standby work; a plain
        pre-JSON file reads as {"addr": <contents>}. {} = no file."""
        import json

        path = os.getenv(NodeEnv.MASTER_BOOTSTRAP, "") or (
            Context.singleton().master_bootstrap_file)
        if not path:
            return {}
        try:
            with open(path) as f:
                raw = f.read().strip()
        except OSError:
            return {}
        if not raw:
            return {}
        if raw.startswith("{"):
            try:
                parsed = json.loads(raw)
                if isinstance(parsed, dict) and parsed.get("addr"):
                    return parsed
            except ValueError:
                return {}
            return {}
        return {"addr": raw}

    @classmethod
    def resolve_master_addr(cls, default: str = "") -> str:
        """Where is the master NOW? The bootstrap file wins (a restarted
        or PROMOTED master atomically rewrites it with its new address +
        a bumped generation); the env contract is the fallback; then the
        caller's default."""
        bootstrap = cls.resolve_bootstrap()
        if bootstrap.get("addr"):
            return str(bootstrap["addr"])
        return os.getenv(NodeEnv.MASTER_ADDR, "") or default

    def reresolve_if_moved(self) -> bool:
        """Re-read the bootstrap file and re-dial when the master moved
        (a promotion/restart while this process was mid-training). The
        AGENT's master-lost loop does this itself; WORKER processes —
        which learn addresses from env at spawn and are deliberately
        not respawned on promotion — call this from their RPC failure
        paths (e.g. parallel/dcn_sync) so a promoted master's slice
        status/coordination serves again without a restart. No-op
        without a bootstrap file."""
        bootstrap = self.resolve_bootstrap()
        addr = str(bootstrap.get("addr") or "")
        if not addr or addr == self.master_addr:
            return False
        coord = str(bootstrap.get("coord_addr") or "")
        logger_note = (f"master moved {self.master_addr} -> {addr} "
                       f"(bootstrap generation "
                       f"{bootstrap.get('generation', '?')}); re-dialing")
        from dlrover_tpu.common.log import default_logger as logger

        logger.warning(logger_note)
        self.reconnect(addr, coord_addr=coord)
        return True

    # -- raw --------------------------------------------------------------
    def _get(self, request: msg.Message) -> msg.Message:
        data = self._stub.get(msg.serialize_message(request),
                              timeout=self._timeout_s)
        return msg.deserialize_message(data)

    def _typed(self, send, request: msg.Message,
               expected: type) -> msg.Message:
        """Send via ``send`` and enforce the response type — a generic
        failure Response becomes a raisable (and retryable) error instead
        of an AttributeError in the caller."""
        response = send(request)
        if not isinstance(response, expected):
            reason = getattr(response, "reason", repr(response))
            raise RuntimeError(
                f"master error for {type(request).__name__}: {reason}"
            )
        return response

    def _get_typed(self, request: msg.Message, expected: type) -> msg.Message:
        return self._typed(self._get, request, expected)

    def _report(self, request: msg.Message) -> msg.Message:
        data = self._stub.report(msg.serialize_message(request),
                                 timeout=self._timeout_s)
        return msg.deserialize_message(data)

    def _report_typed(self, request: msg.Message,
                      expected: type) -> msg.Message:
        return self._typed(self._report, request, expected)

    # -- coordination-tier routing ----------------------------------------
    @staticmethod
    def _is_hot_key(key: str) -> bool:
        return key.startswith(HOT_KV_PREFIXES)

    def _coord_send(self, kind: str, request: msg.Message,
                    timeout_s: Optional[float] = None) -> msg.Message:
        """Send a coordination RPC via the coordination tier when one is
        dialed, falling back to the main tier (which answers every
        coordination RPC too — single-tier masters, mid-promotion
        windows) on any transport failure."""
        payload = msg.serialize_message(request)
        timeout = timeout_s if timeout_s is not None else self._timeout_s
        stub = self._coord_stub
        if stub is not None and time.monotonic() >= \
                self._coord_down_until:
            try:
                send = stub.get if kind == "get" else stub.report
                return msg.deserialize_message(
                    send(payload, timeout=timeout))
            except Exception:  # noqa: BLE001 — grpc errors vary
                self._coord_down_until = time.monotonic() + 5.0
        send = self._stub.get if kind == "get" else self._stub.report
        return msg.deserialize_message(send(payload, timeout=timeout))

    def close(self) -> None:
        self._channel.close()
        if self._coord_channel is not None:
            self._coord_channel.close()

    # -- dynamic sharding -------------------------------------------------
    @retry_rpc()
    def report_dataset_shard_params(self, params: msg.DatasetShardParams
                                    ) -> bool:
        return self._report(params).success

    @retry_rpc(retries=3)
    def get_task(self, dataset_name: str) -> msg.Task:
        return self._get_typed(
            msg.TaskRequest(dataset_name=dataset_name,
                            worker_id=self.node_id),
            msg.Task,
        )

    @retry_rpc(retries=3)
    def report_task_result(self, dataset_name: str, task_id: int,
                           success: bool = True, err: str = "") -> bool:
        return self._report(msg.TaskResult(
            dataset_name=dataset_name, task_id=task_id,
            worker_id=self.node_id, success=success, err_message=err,
        )).success

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        result = self._get_typed(
            msg.ShardCheckpointRequest(dataset_name=dataset_name),
            msg.ShardCheckpoint,
        )
        return result.content

    def report_shard_checkpoint(self, content: str) -> bool:
        return self._report(msg.ShardCheckpoint(content=content)).success

    def get_dataset_epoch(self, dataset_name: str) -> int:
        return self._get_typed(
            msg.DatasetEpochInfo(dataset_name=dataset_name),
            msg.DatasetEpochInfo,
        ).epoch

    def get_task_counts(self, dataset_name: str) -> Tuple[int, int]:
        """(todo, doing) task counts of a registered dataset — progress
        introspection for tools and tests (the servicer answered this
        endpoint since PR 2; graftlint GL402 found it had no wrapper)."""
        result = self._get_typed(
            msg.TaskCounts(dataset_name=dataset_name), msg.TaskCounts)
        return result.todo, result.doing

    # -- rendezvous -------------------------------------------------------
    @retry_rpc()
    def join_rendezvous(self, local_world_size: int,
                        rdzv_name: str = RendezvousName.TRAINING) -> int:
        """Returns the rendezvous round this node was placed in."""
        from dlrover_tpu.obs import current_context

        result = self._report_typed(msg.JoinRendezvousRequest(
            node_id=self.node_id,
            node_rank=self.node_rank,
            local_world_size=local_world_size,
            rdzv_name=rdzv_name,
            node_ip=local_ip(),
            trace=current_context() or {},
            slice_id=self.slice_id,
        ), msg.JoinRendezvousResult)
        if result.generation:
            self.master_generation = result.generation
        self.last_restore_plan_json = getattr(result,
                                              "restore_plan_json", "")
        self.last_shard_plan_json = getattr(result,
                                            "shard_plan_json", "")
        self.set_coord_addr(getattr(result, "coord_addr", ""))
        return result.round

    def reconnect_report(self, local_world_size: int = 1,
                         rdzv_name: str = RendezvousName.TRAINING,
                         rdzv_round: int = -1) -> msg.ReconnectResult:
        """Re-register with a (possibly restarted) master after a
        master-lost episode. Deliberately undecorated: the caller's
        reconnect loop owns pacing, and a single clean failure per dial
        attempt keeps that loop's backoff honest."""
        result = self._report_typed(msg.ReconnectRequest(
            node_id=self.node_id,
            node_rank=self.node_rank,
            node_type=self.node_type,
            local_world_size=local_world_size,
            rdzv_name=rdzv_name,
            generation=self.master_generation,
            rdzv_round=rdzv_round,
            slice_id=self.slice_id,
        ), msg.ReconnectResult)
        if result.generation:
            self.master_generation = result.generation
        # a restarted/promoted master's coordination tier is a fresh
        # bind; adopt whatever it advertises (possibly "")
        self.set_coord_addr(getattr(result, "coord_addr", ""))
        return result

    @retry_rpc()
    def leave_rendezvous(self, rdzv_name: str = RendezvousName.TRAINING
                         ) -> bool:
        """Withdraw from an uncompleted round (poll deadline gave up)."""
        return self._report(msg.LeaveRendezvousRequest(
            node_id=self.node_id,
            node_rank=self.node_rank,
            rdzv_name=rdzv_name,
        )).success

    @retry_rpc(retries=3)
    def get_comm_world(self, rdzv_name: str = RendezvousName.TRAINING
                       ) -> Tuple[int, int, Dict[int, int]]:
        world = self._get_typed(
            msg.CommWorldRequest(node_id=self.node_rank,
                                 rdzv_name=rdzv_name),
            msg.CommWorld,
        )
        if world.rdzv_name and world.rdzv_name != rdzv_name:
            # the echo field guards against a cross-wired dispatch (a
            # stale/misrouted response adopted as this rendezvous's
            # world would re-form the wrong protocol's membership)
            raise RuntimeError(
                f"comm world for {world.rdzv_name!r}, "
                f"asked for {rdzv_name!r}")
        return world.round, world.group, world.world

    @retry_rpc(retries=3)
    def num_nodes_waiting(self, rdzv_name: str = RendezvousName.TRAINING
                          ) -> int:
        result = self._get_typed(
            msg.WaitingNodeNumRequest(node_id=self.node_rank,
                                      rdzv_name=rdzv_name),
            msg.WaitingNodeNum,
        )
        return result.waiting_num

    # -- peer-to-peer restore ---------------------------------------------
    @retry_rpc(retries=3)
    def report_peer_store(self, addr: str, step: int, keys,
                          total_bytes: int = 0,
                          rdzv_name: str = RendezvousName.TRAINING
                          ) -> bool:
        """Advertise (step >= 0) or withdraw (step < 0) this host's
        staged peer-state cache with the master's donor registry."""
        return self._report(msg.PeerStoreReport(
            node_id=self.node_id, node_rank=self.node_rank, addr=addr,
            step=step, rdzv_name=rdzv_name, keys=list(keys),
            total_bytes=total_bytes, slice_id=self.slice_id,
        )).success

    @retry_rpc(retries=3)
    def get_slice_status(self, rdzv_name: str = RendezvousName.TRAINING
                         ) -> dict:
        """The master's slice registry view + the job step high-water
        mark ({} = no slice registry / master predates it) — the
        cross-slice gradient sync's present set
        (parallel/dcn_sync.py)."""
        # per-step traffic: the coordination tier answers when split out
        result = self._typed(
            lambda request: self._coord_send("get", request),
            msg.SliceStatusRequest(
                node_id=self.node_id, node_rank=self.node_rank,
                rdzv_name=rdzv_name), msg.SliceStatus)
        return self._json_dict(result.status_json)

    @retry_rpc(retries=3)
    def get_shard_plan(self, rdzv_name: str = RendezvousName.TRAINING
                       ) -> dict:
        """The current parallelism plan for this rank's world
        (parallel/planner.py; {} = no plan / master predates it)."""
        import json

        result = self._get_typed(msg.ShardPlanRequest(
            node_id=self.node_id, node_rank=self.node_rank,
            rdzv_name=rdzv_name), msg.ShardPlanResult)
        if not result.found or not result.plan_json:
            return {}
        try:
            plan = json.loads(result.plan_json)
        except json.JSONDecodeError:
            return {}
        if not isinstance(plan, dict):
            return {}
        # the envelope's epoch/generation are authoritative (the plan
        # dict predates them in old masters): staleness checks read the
        # plan, so make sure the stamps are present on it
        plan.setdefault("epoch", result.epoch)
        plan.setdefault("generation", result.generation)
        return plan

    @retry_rpc(retries=3)
    def get_restore_plan(self, rdzv_name: str = RendezvousName.TRAINING,
                         stripe: bool = False) -> dict:
        """A fresh peer-restore plan for this rank ({} = no donors).
        ``stripe``: the resharding-migration mode — entries list every
        same-step holder so the receiver fetches byte ranges of one
        shard from several donors in parallel."""
        import json

        result = self._get_typed(msg.RestorePlanRequest(
            node_id=self.node_id, node_rank=self.node_rank,
            rdzv_name=rdzv_name, stripe=stripe), msg.RestorePlan)
        if not result.found or not result.plan_json:
            return {}
        try:
            plan = json.loads(result.plan_json)
        except json.JSONDecodeError:
            return {}
        if not isinstance(plan, dict):
            return {}
        # same contract as get_shard_plan: the envelope's epoch is
        # authoritative, and the commit-time staleness guard
        # (get_restore_epoch) compares against the stamp on the plan —
        # a plan without it would always look fresh
        plan.setdefault("epoch", result.epoch)
        return plan

    @retry_rpc(retries=3)
    def get_restore_epoch(self, rdzv_name: str = RendezvousName.TRAINING
                          ) -> int:
        """The current world epoch — the staleness guard's commit-time
        check against the epoch a restore plan was computed at."""
        return self._get_typed(msg.RestorePlanRequest(
            node_id=self.node_id, node_rank=self.node_rank,
            rdzv_name=rdzv_name, epoch_only=True),
            msg.RestorePlan).epoch

    def report_network_status(self, normal: bool, elapsed: float) -> bool:
        return self._report(msg.NetworkStatusReport(
            node_id=self.node_rank, normal=normal, elapsed_time=elapsed,
        )).success

    def get_network_check_verdict(self) -> msg.NetworkCheckVerdict:
        return self._get_typed(
            msg.NetworkCheckResultRequest(node_id=self.node_rank),
            msg.NetworkCheckVerdict,
        )

    # -- kv store ---------------------------------------------------------
    # hot-prefix keys (dcn/ gradient exchange, coord/ barriers) route to
    # the coordination tier when the master split one out; cold keys
    # stay on the main tier for its write-through snapshot durability
    def kv_set(self, key: str, value: bytes) -> bool:
        request = msg.KeyValuePair(key=key, value=value)
        if self._is_hot_key(key):
            return self._coord_send("report", request).success
        return self._report(request).success

    def kv_get(self, key: str) -> bytes:
        send = ((lambda request: self._coord_send("get", request))
                if self._is_hot_key(key) else self._get)
        return self._typed(send, msg.KVGetRequest(key=key),
                           msg.KeyValuePair).value

    def kv_add(self, key: str, amount: int) -> int:
        send = ((lambda request: self._coord_send("report", request))
                if self._is_hot_key(key) else self._report)
        return self._typed(send,
                           msg.KVAddRequest(key=key, amount=amount),
                           msg.KVIntResult).value

    def kv_wait(self, key: str, timeout_s: float = 300.0) -> bytes:
        """Block until the key appears: the master holds each RPC open on a
        condition variable (KVWaitRequest) for up to ~20 s per window."""
        deadline = time.time() + timeout_s
        hot = self._is_hot_key(key)
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError(f"kv_wait timed out on {key!r}")
            window = min(remaining, 20.0)
            request = msg.KVWaitRequest(keys=[key], timeout_s=window)
            if hot:
                result = self._coord_send(
                    "get", request, timeout_s=window + self._timeout_s)
            else:
                result = self._get(request)
            if getattr(result, "success", False):
                return self.kv_get(key)

    # -- health / status --------------------------------------------------
    def report_global_step(self, step: int, step_time_s: float = 0.0,
                           data_wait_fraction: float = -1.0,
                           mfu: float = -1.0,
                           degraded_steps: int = 0,
                           hbm_peak_bytes: float = 0.0,
                           plan_generation: int = -1) -> bool:
        """Step progress, optionally with the sender's windowed speed
        evidence (mean step wall time + data-wait fraction from the
        worker's phase timeline, achieved MFU from its FLOPs model) —
        the diagnosis engine's straggler / data-bound / collapse
        input and the goodput ledger's productive-time accrual.
        ``degraded_steps``: steps in this window the sender's slice
        took with a renormalized (peer-slice-absent) gradient mean.
        ``hbm_peak_bytes``: the window's device-truth HBM allocator
        peak (obs/device.py; 0 = backend has no memory stats).
        ``plan_generation``: the applied shard plan's generation —
        calibration attributes the timing evidence by it (-1 =
        unknown, -2 = running the fallback mesh, see
        GlobalStepReport)."""
        # timestamp is deliberately unread master-side: the speed
        # window keys every delta on the MASTER clock (mixing sender
        # clocks would put cross-host skew in steps/s); the field rides
        # for wire-capture forensics only
        return self._report(msg.GlobalStepReport(  # graftlint: disable=GL401
            node_id=self.node_id, step=step, timestamp=time.time(),
            node_rank=self.node_rank, step_time_s=step_time_s,
            data_wait_fraction=data_wait_fraction, mfu=mfu,
            degraded_steps=degraded_steps,
            hbm_peak_bytes=hbm_peak_bytes,
            plan_generation=plan_generation,
        )).success

    # -- diagnosis --------------------------------------------------------
    def poll_diagnosis_actions(self) -> list:
        """Actions the master's diagnosis engine addressed to this rank
        (single delivery — the caller must execute or drop them)."""
        import json

        result = self._get_typed(
            msg.DiagnosisActionRequest(node_id=self.node_id,
                                       node_rank=self.node_rank),
            msg.DiagnosisActions,
        )
        if not result.actions_json:
            return []
        try:
            actions = json.loads(result.actions_json)
        except json.JSONDecodeError:
            return []
        return actions if isinstance(actions, list) else []

    def get_diagnosis_reports(self, limit: int = 0) -> list:
        """Recent DiagnosisReport dicts from the master (tools/diagnose)."""
        import json

        result = self._get_typed(
            msg.DiagnosisReportRequest(limit=limit),
            msg.DiagnosisReports,
        )
        if not result.reports_json:
            return []
        try:
            reports = json.loads(result.reports_json)
        except json.JSONDecodeError:
            return []
        return reports if isinstance(reports, list) else []

    def report_resource_stats(self, stats: msg.NodeResourceStats) -> bool:
        return self._report(stats).success

    def report_heartbeat(self) -> bool:
        return self._report(msg.NodeHeartbeat(
            node_id=self.node_id, node_type=self.node_type,
            timestamp=time.time(), node_rank=self.node_rank)).success

    def report_failure(self, error_data: str, level: str,
                       restart_count: int = 0,
                       exit_kind: str = "") -> bool:
        return self._report(msg.NodeFailureReport(
            node_id=self.node_id, node_rank=self.node_rank,
            error_data=error_data, level=level,
            restart_count=restart_count, exit_kind=exit_kind,
        )).success

    @retry_rpc(retries=3)
    def report_drain(self, deadline: float, reason: str = "",
                     phase: str = "notice") -> msg.DrainResult:
        """Announce (phase="notice") or conclude (phase="complete") this
        node's preemption drain. A modest retry budget: the drain window
        is finite — better to proceed with the local emergency
        checkpoint than to spend the grace period retrying RPCs."""
        return self._report_typed(msg.DrainReport(
            node_id=self.node_id, node_rank=self.node_rank,
            deadline=deadline, reason=reason, phase=phase,
        ), msg.DrainResult)

    def report_node_address(self, addr: str) -> bool:
        return self._report(msg.NodeAddressReport(
            node_id=self.node_id, node_rank=self.node_rank, addr=addr,
        )).success

    def report_model_info(self, param_count: int, param_bytes: int,
                          flops_per_step: float = 0.0,
                          batch_size: int = 0, seq_len: int = 0,
                          flops_per_token: float = 0.0,
                          peak_flops_per_chip: float = 0.0,
                          chips: int = 0,
                          flops_source: str = "",
                          tensor_divisor: int = 0,
                          fsdp_divisor: int = 0,
                          effective_global_batch: int = 0) -> bool:
        """Static model stats for the resource optimizer (reference:
        profile_extractor reporting ModelInfo) plus the FLOPs model
        that turns the master's tokens/s series into MFU gauges and
        the dim-divisibility granules the parallelism planner filters
        tensor/fsdp candidates by (parallel/planner.py)."""
        return self._report(msg.ModelInfo(
            param_count=param_count, param_bytes=param_bytes,
            flops_per_step=flops_per_step, batch_size=batch_size,
            seq_len=seq_len, flops_per_token=flops_per_token,
            peak_flops_per_chip=peak_flops_per_chip, chips=chips,
            flops_source=flops_source, tensor_divisor=tensor_divisor,
            fsdp_divisor=fsdp_divisor,
            effective_global_batch=effective_global_batch,
        )).success

    @staticmethod
    def _json_dict(text: str) -> dict:
        """A JSON-dict RPC payload field, or {} — the shared contract
        of every "{} = master predates this" JSON-carrying result."""
        import json

        if not text:
            return {}
        try:
            payload = json.loads(text)
        except json.JSONDecodeError:
            return {}
        return payload if isinstance(payload, dict) else {}

    def query_timeseries(self, name: str = "", labels=None,
                         window_s: float = 0.0,
                         resolution_s: float = 0.0) -> dict:
        """Windowed, aligned history from the master's time-series
        store (obs/tsdb.py): {"series": [...], "tiers": [...],
        "stats": {...}} — or {"names": [...]} with an empty name.
        {} = master predates the store / store disabled."""
        result = self._get_typed(msg.TimeSeriesQuery(
            name=name, labels=dict(labels or {}),
            window_s=window_s, resolution_s=resolution_s),
            msg.TimeSeriesResult)
        return self._json_dict(result.result_json)

    def get_plan_calibration(self) -> dict:
        """The planner calibration table + learned axis discounts
        (parallel/calibration.py): {"table": [...], "discounts": {}}.
        {} = master predates calibration."""
        result = self._get_typed(msg.PlanCalibrationRequest(),
                                 msg.PlanCalibrationReport)
        return self._json_dict(result.report_json)

    def get_goodput(self, window_s: float = 0.0) -> dict:
        """The master's goodput-ledger snapshot (tools/goodput.py)."""
        result = self._get_typed(msg.GoodputRequest(window_s=window_s),
                                 msg.GoodputReport)
        return self._json_dict(result.report_json)

    def query_steptrace(self, start_step: int = -1, end_step: int = -1,
                        last_n: int = 0) -> dict:
        """Assembled per-step critical paths from the master's
        StepTraceAssembler (master/steptrace.py): {"version", "steps",
        "summary"}. {} = master predates steptrace."""
        result = self._get_typed(msg.StepTraceRequest(
            start_step=start_step, end_step=end_step, last_n=last_n),
            msg.StepTraceResult)
        return self._json_dict(result.result_json)

    def get_autoscale_status(self) -> dict:
        """The fleet controller's decision history + guardrail state
        (brain/fleet_controller.py): {"decisions", "watch",
        "quarantine", "offers", ...}. {} = controller disabled or
        master predates it."""
        result = self._get_typed(msg.AutoscaleStatusRequest(),
                                 msg.AutoscaleStatus)
        return self._json_dict(result.status_json)

    def probe_clock(self) -> float:
        """One NTP-style clock probe: the master's wall clock, or -1.0
        on failure / a master that predates ClockProbe. Deliberately a
        single attempt on the RAW path — retry_rpc's backoff between
        attempts would inflate the measured RTT, which IS the
        uncertainty bound ClockSync stamps into records."""
        try:
            result = self._get(msg.ClockProbe(node_id=self.node_id))
        except Exception:  # noqa: BLE001 — droppable by contract
            return -1.0
        return float(getattr(result, "server_ts", -1.0) or -1.0)

    def report_telemetry(self, samples=None, spans=None,
                         steptrace=None) -> bool:
        """Push metric samples + finished span dicts + per-step trace
        records to the master (obs/). Best-effort by contract: callers
        treat a False/raise as droppable telemetry."""
        import json

        if not samples and not spans and not steptrace:
            return True
        return self._report(msg.TelemetryReport(
            node_id=self.node_id,
            node_rank=self.node_rank,
            node_type=self.node_type,
            samples=list(samples or ()),
            spans_json=json.dumps(spans) if spans else "",
            steptrace_json=json.dumps(steptrace) if steptrace else "",
        )).success

    def get_paral_config(self) -> msg.ParallelConfig:
        return self._get_typed(
            msg.ParallelConfigRequest(node_id=self.node_id),
            msg.ParallelConfig,
        )

    def report_scale_request(self, node_type: str, count: int,
                             cpu: float = 0.0,
                             memory_mb: float = 0.0) -> bool:
        """Relay a manual scale plan to the master's job manager (the
        ScalePlan-CRD analogue; the servicer answered this endpoint
        since PR 2 — graftlint GL402 found it had no wrapper, leaving
        tools no sanctioned way to request a resize)."""
        return self._report(msg.ScaleRequest(
            node_type=node_type, count=count, cpu=cpu,
            memory_mb=memory_mb,
        )).success

    def get_job_status(self) -> msg.JobStatus:
        return self._get_typed(msg.JobStatusRequest(), msg.JobStatus)

    # -- barriers / PS versions -------------------------------------------
    def join_sync(self, sync_name: str) -> bool:
        return self._report(msg.SyncJoinRequest(
            sync_name=sync_name, node_id=self.node_id)).success

    def sync_finished(self, sync_name: str) -> bool:
        return self._get(msg.SyncQueryRequest(sync_name=sync_name)).success

    def finish_sync(self, sync_name: str) -> bool:
        return self._report(
            msg.SyncFinishRequest(sync_name=sync_name)).success

    def update_cluster_version(self, version_type: str, version: int,
                               task_type: str = "worker",
                               task_id: Optional[int] = None) -> bool:
        return self._report(msg.ClusterVersionRequest(
            task_type=task_type,
            task_id=task_id if task_id is not None else self.node_id,
            version_type=version_type, version=version,
        )).success

    def get_cluster_version(self, version_type: str,
                            task_type: str = "worker",
                            task_id: Optional[int] = None) -> int:
        return self._get_typed(msg.ClusterVersionRequest(
            task_type=task_type,
            task_id=task_id if task_id is not None else self.node_id,
            version_type=version_type,
        ), msg.ClusterVersion).version

    # -- singleton --------------------------------------------------------
    @classmethod
    def singleton(cls) -> "MasterClient":
        if cls._singleton is None:
            addr = os.getenv(NodeEnv.MASTER_ADDR, "")
            if not addr:
                raise RuntimeError(
                    f"{NodeEnv.MASTER_ADDR} is not set; is this process "
                    "running under dlrover-tpu-run?"
                )
            node_id = int(os.getenv(NodeEnv.NODE_ID, "0"))
            node_rank = int(os.getenv(NodeEnv.NODE_RANK, str(node_id)))
            cls._singleton = cls(addr, node_id, node_rank)
        return cls._singleton

    @classmethod
    def reset_singleton(cls) -> None:
        cls._singleton = None

