"""Preemption notices: pluggable sources + the agent→worker drain file.

TPU spot/maintenance events arrive with an advance notice window; a
preempted VM that is treated like a crash loses up to a liveness
timeout of survivor progress plus every unsaved step. This module turns
the notice into a *planned* departure:

- :class:`PreemptionWatcher` polls pluggable sources on the agent —
  SIGTERM with a grace window (chained AFTER the flight-recorder dump
  handler, never clobbering it), a JSON notice file
  (``$DLROVER_TPU_PREEMPTION_NOTICE`` — what the chaos ``preempt``
  fault writes), and a k8s-style static env deadline
  (``$DLROVER_TPU_PREEMPTION_AT``).
- The agent reports ``drain(rank, deadline)`` to the master
  (``DrainReport`` RPC) and publishes a drain request the worker's step
  loop consumes at the next step boundary
  (:func:`write_drain_request` / :class:`DrainRequestSource` — the same
  atomic-file contract as the profiler's request channel).

The drain request carries ``exit``: True means save-and-exit with the
clean-drain code (this node is going away); False means save-and-keep-
running (the master's urgent ``checkpoint:{rank}`` fan-out to the
survivors).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger


@dataclasses.dataclass
class PreemptionNotice:
    """One advance notice: this host disappears at ``deadline``."""

    deadline: float              # unix ts
    reason: str = ""
    source: str = ""             # "sigterm" | "file" | "env"

    @property
    def grace_s(self) -> float:
        return max(0.0, self.deadline - time.time())


class NoticeSource:
    """One way a preemption notice can arrive; ``poll()`` returns the
    notice once (idempotent None afterwards)."""

    name = "base"

    def poll(self) -> Optional[PreemptionNotice]:
        raise NotImplementedError

    def close(self) -> None:
        """Release anything installed (signal handlers)."""


class FileNoticeSource(NoticeSource):
    """JSON notice file (``{"deadline": ts}`` or ``{"grace_s": n}``,
    optional ``"reason"``) — the contract the chaos ``preempt`` fault
    and platform node-termination hooks write, atomically."""

    name = "file"

    def __init__(self, path: str = ""):
        self._path = path or os.environ.get(
            NodeEnv.PREEMPTION_NOTICE_FILE, "")
        self._warned_stale = False

    def poll(self) -> Optional[PreemptionNotice]:
        if not self._path:
            return None
        try:
            st = os.stat(self._path)
            with open(self._path) as f:
                raw = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        if not isinstance(raw, dict):
            return None
        deadline = float(raw.get("deadline", 0.0) or 0.0)
        if deadline <= 0.0:
            grace = float(raw.get("grace_s",
                                  Context.singleton()
                                  .preempt_default_grace_s))
            # anchored to the WRITE time, not the read time: a
            # grace-only notice left behind by a previous incarnation
            # would otherwise look fresh on every read and re-drain
            # each relaunched agent forever
            deadline = st.st_mtime + grace
        if deadline <= time.time():
            # the window already closed and this process is still
            # alive: the drain was cancelled (or the file is a
            # leftover) — draining now would skip the checkpoint AND
            # loop, since a DRAINED relaunch is never budget-charged
            if not self._warned_stale:
                self._warned_stale = True
                logger.warning(
                    "ignoring stale preemption notice %s (deadline "
                    "%.0fs in the past)", self._path,
                    time.time() - deadline)
            return None
        self._warned_stale = False
        return PreemptionNotice(deadline=deadline,
                                reason=str(raw.get("reason", "")),
                                source=self.name)


class EnvNoticeSource(NoticeSource):
    """k8s-style static deadline: ``$DLROVER_TPU_PREEMPTION_AT`` holds a
    unix timestamp set at pod creation (a scheduled maintenance window /
    spot VM with a known reclaim time). Fires once the deadline is
    within the default grace horizon — early enough to checkpoint, late
    enough not to drain a week ahead of a known maintenance date."""

    name = "env"

    def poll(self) -> Optional[PreemptionNotice]:
        raw = os.environ.get(NodeEnv.PREEMPTION_AT, "")
        if not raw:
            return None
        try:
            deadline = float(raw)
        except ValueError:
            return None
        now = time.time()
        if deadline <= now:
            # the env var is static by design (set in the pod spec):
            # once the window has passed, a replacement pod inheriting
            # the same spec must NOT drain itself at startup
            return None
        # preempt_env_horizon_s, not the bare-SIGTERM grace, when set:
        # a job whose full save outlasts the 30s grace needs the drain
        # to START earlier than that, and a known-in-advance deadline
        # is exactly the case where it can
        ctx = Context.singleton()
        horizon = max(ctx.preempt_env_horizon_s
                      or ctx.preempt_default_grace_s, 1.0)
        if deadline - now > horizon:
            return None
        return PreemptionNotice(deadline=deadline,
                                reason="scheduled preemption (env)",
                                source=self.name)


class SignalNoticeSource(NoticeSource):
    """SIGTERM with grace: the platform's last-resort notice. The
    handler CHAINS the previous disposition (the flight recorder's dump
    handler from PR 2 — both must fire; install order in the agent puts
    this source underneath so the recorder's handler calls through to
    it). The deadline is now + ``preempt_default_grace_s`` — a bare
    SIGTERM carries no better information."""

    name = "sigterm"

    def __init__(self, signum: int = signal.SIGTERM):
        self._signum = signum
        self._notice: Optional[PreemptionNotice] = None
        self._prev: Any = None
        self._handler: Any = None
        self._installed = False

    def install(self) -> None:
        """Main-thread-only (CPython signal contract)."""
        if self._installed:
            return

        def _handler(signum, frame):
            if self._notice is None:
                grace = Context.singleton().preempt_default_grace_s
                self._notice = PreemptionNotice(
                    deadline=time.time() + grace,
                    reason=f"signal {signum}", source=self.name)
                logger.warning(
                    "SIGTERM: treating as a preemption notice "
                    "(grace %.0fs)", grace)
            prev = self._prev
            if callable(prev):
                prev(signum, frame)
            # SIG_DFL deliberately NOT re-raised here: the whole point
            # of the notice is a graceful drain instead of dying now

        self._handler = _handler
        self._prev = signal.signal(self._signum, _handler)
        self._installed = True

    def poll(self) -> Optional[PreemptionNotice]:
        notice, self._notice = self._notice, None
        return notice

    def close(self) -> None:
        if not self._installed:
            return
        self._installed = False
        try:
            if signal.getsignal(self._signum) is self._handler:
                signal.signal(self._signum, self._prev)
            # else: something chained ON TOP of this source after
            # install (the flight recorder's dump handler in the
            # agent) — restoring _prev would silently rip that handler
            # out with ours. Leave the chain intact: our handler only
            # records a notice nobody polls anymore and calls through.
        except ValueError:
            pass          # not the main thread: leave the disposition


def default_sources(install_signal: bool = True,
                    notice_file: str = "") -> List[NoticeSource]:
    """The standard source set: notice file, static env deadline, and —
    main thread only (CPython signal contract) — SIGTERM with grace."""
    sources: List[NoticeSource] = [FileNoticeSource(notice_file),
                                   EnvNoticeSource()]
    if install_signal and (threading.current_thread()
                           is threading.main_thread()):
        sig = SignalNoticeSource()
        sig.install()
        sources.append(sig)
    return sources


class PreemptionWatcher:
    """Polls the notice sources; delivers the FIRST notice to
    ``on_notice`` exactly once. The callback runs on the watcher thread
    and must only flip an event the agent's main loop consumes (worker
    lifecycle stays single-threaded, like the hang-event contract)."""

    def __init__(self, on_notice: Callable[[PreemptionNotice], None],
                 sources: Optional[List[NoticeSource]] = None,
                 poll_s: Optional[float] = None):
        self._on_notice = on_notice
        self._sources = (sources if sources is not None
                         else default_sources())
        self._poll_s = (poll_s if poll_s is not None
                        else Context.singleton().preempt_notice_poll_s)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._delivered = False

    def poll_once(self) -> Optional[PreemptionNotice]:
        """One sweep over the sources; delivers on first hit."""
        if self._delivered:
            return None
        for source in self._sources:
            try:
                notice = source.poll()
            except Exception:  # noqa: BLE001 — one source, not the watch
                logger.exception("preemption source %s failed",
                                 source.name)
                continue
            if notice is not None:
                self._delivered = True
                logger.warning(
                    "preemption notice (%s): departing in %.0fs (%s)",
                    notice.source, notice.grace_s,
                    notice.reason or "no reason")
                self._on_notice(notice)
                return notice
        return None

    def start(self) -> None:
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._poll_s):
                if self.poll_once() is not None:
                    return

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="preemption-watcher")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        for source in self._sources:
            source.close()


# ---------------------------------------------------------------------------
# Agent → worker drain-request channel (atomic file, one os.stat per step)
# ---------------------------------------------------------------------------


def write_drain_request(path: str, seq: int, deadline: float,
                        reason: str = "", exit_worker: bool = True) -> None:
    """Agent side: atomically publish a drain/checkpoint request for the
    worker's step loop. A new ``seq`` supersedes any previous request."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"seq": int(seq), "deadline": float(deadline),
                   "reason": reason, "exit": bool(exit_worker)}, f)
    os.replace(tmp, path)


class DrainRequestSource:
    """Worker side: polled once per step from the step loop's thread.
    Cheap when idle (one ``os.stat`` of a usually-absent file); a
    respawned worker re-reads the file, so ``seq`` dedup rides on the
    ``.done`` acknowledgement the loop writes after consuming a
    save-and-continue request (an exit request never needs dedup — the
    process is gone)."""

    def __init__(self, path: str = ""):
        self._path = path or os.environ.get(
            NodeEnv.DRAIN_REQUEST_FILE, "")
        self._last_stat = None
        self._handled_seq = -1
        if self._path:
            try:
                with open(self._path + ".done") as f:
                    self._handled_seq = int(json.load(f).get("seq", -1))
            except (OSError, json.JSONDecodeError, ValueError, TypeError):
                pass

    def poll(self) -> Optional[Dict[str, Any]]:
        if not self._path:
            return None
        try:
            st = os.stat(self._path)
        except OSError:
            return None
        # inode in the key: every write is a tmp+rename (fresh inode),
        # so a rewrite inside one coarse-mtime tick (1 s on some NFS)
        # still changes the key — mtime alone would skip it forever
        stat_key = (st.st_ino, st.st_mtime_ns, st.st_size)
        if stat_key == self._last_stat:
            return None
        self._last_stat = stat_key
        try:
            with open(self._path) as f:
                raw = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        if not isinstance(raw, dict):
            return None
        seq = int(raw.get("seq", 0) or 0)
        if seq <= self._handled_seq:
            return None
        self._handled_seq = seq
        return raw

    def acknowledge(self, seq: int) -> None:
        """Record a consumed save-and-continue request so a respawn does
        not replay it."""
        if not self._path:
            return
        try:
            tmp = self._path + ".done.tmp"
            with open(tmp, "w") as f:
                json.dump({"seq": int(seq), "ts": time.time()}, f)
            os.replace(tmp, self._path + ".done")
        except OSError:
            pass
