"""Kubernetes operator: ElasticJob/ScalePlan watch → reconcile → pod CRUD.

Capability parity: the Go operator process —
`ElasticJobReconciler.Reconcile` (pkg/controllers/elasticjob_controller.go:85)
creating exactly one master pod + service per job
(pkg/controllers/master/master.go:53-162, DLROVER_MASTER_ADDR injection
:188), job phase sync from replica statuses, and the ScalePlanReconciler
relay of manual scale requests to the master. The decision core is the
shared native reconcile (native/reconciler.cpp via operator/native.py); this
module is the k8s shell: CR watch streams, pod CRUD through the
zero-dependency REST client, and CR status patches.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.operator.controller import ElasticJobController
from dlrover_tpu.operator.crd import (
    ELASTICJOB_PLURAL,
    GROUP,
    SCALEPLAN_PLURAL,
    VERSION,
    ElasticJob,
    ScalePlan,
)
from dlrover_tpu.scheduler.kubernetes import (
    K8sClient,
    build_pod_manifest,
    pod_to_fields,
)

MASTER_PORT = 50001


class _PodView:
    """The pod surface the controller's observe() needs."""

    def __init__(self, fields: Dict[str, Any]):
        self.name = fields["name"]
        self.node_type = fields["node_type"]
        self.status = fields["status"]
        self.terminating = fields.get("terminating", False)


class K8sJobCluster:
    """LocalCluster-compatible view of ONE job's pods over the k8s API.

    The controller observes through list_pods and acts through
    create_master/delete_pod; worker pods are created by the MASTER
    (pod scaler), exactly as in the reference — the operator only owns
    the master pod + service (master/master.go:69,145).
    """

    def __init__(self, job: ElasticJob, client: K8sClient):
        self.job = job
        self._client = client

    # -- controller observe surface ------------------------------------
    def list_pods(self, node_type: Optional[str] = None):
        selector = f"dlrover-tpu/job={self.job.name}"
        if node_type:
            selector += f",dlrover-tpu/type={node_type}"
        views = [_PodView(pod_to_fields(p))
                 for p in self._client.list_pods(selector)]
        # A pod under graceful deletion must read as gone, or the
        # reconciler re-fires RELAUNCH_MASTER every tick while the old
        # pod lingers Terminating and burns the restart budget.
        return [v for v in views if not v.terminating]

    def delete_pod(self, name: str) -> bool:
        return self._client.delete_pod(name)

    # -- controller act surface ----------------------------------------
    @property
    def master_addr(self) -> str:
        """The in-cluster service address injected as
        DLROVER_TPU_MASTER_ADDR (reference: master/master.go:188)."""
        return (f"{self.job.name}-dlrover-master."
                f"{self.job.namespace}:{MASTER_PORT}")

    def create_master(self, ordinal: int = 0) -> str:
        """Create the master pod + stable service; returns the address.
        `ordinal` is the restart count — each relaunch gets a fresh pod
        name so it cannot 409 against the old pod's graceful deletion."""
        spec = self.job.spec.replica_specs.get(
            "master", self.job.spec.replica_specs.get(NodeType.WORKER))
        image = spec.image if spec else ""
        manifest = build_pod_manifest(
            job_name=self.job.name,
            node_type=NodeType.MASTER,
            node_id=ordinal,
            rank_index=0,
            image=image,
            # The master reads its own ElasticJob CR to learn the replica
            # specs and runs the pod scaler/watcher (run_master_main's
            # k8s platform path) — the operator only conveys identity.
            command=(f"python -m dlrover_tpu.master.job_master "
                     f"--port {MASTER_PORT} --platform k8s "
                     f"--job-name {self.job.name} "
                     f"--namespace {self.job.namespace}"),
            master_addr=self.master_addr,
            node_num=1,
            owner_ref=(self.job.owner_reference()
                       if self.job.uid else None),
        )
        self._client.create_pod(manifest)
        self._client.create_service({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{self.job.name}-dlrover-master",
                **({"ownerReferences": [self.job.owner_reference()]}
                   if self.job.uid else {}),
            },
            "spec": {
                "selector": {
                    "dlrover-tpu/job": self.job.name,
                    "dlrover-tpu/type": NodeType.MASTER,
                },
                "ports": [{"port": MASTER_PORT,
                           "targetPort": MASTER_PORT}],
            },
        })
        return self.master_addr


class K8sElasticJobOperator:
    """The operator main loop: one ElasticJobController per CR."""

    def __init__(self, namespace: str = "default",
                 client: Optional[K8sClient] = None,
                 reconcile_interval_s: float = 2.0):
        self._client = client or K8sClient(namespace)
        self._namespace = namespace
        self._interval_s = reconcile_interval_s
        self._controllers: Dict[str, ElasticJobController] = {}
        self._backends: Dict[str, K8sJobCluster] = {}
        self._patched_phase: Dict[str, str] = {}
        self._relayed_plans: set = set()
        # plans whose owner job was not tracked yet (the two watch
        # streams race); retried every reconcile tick
        self._orphan_plans: Dict[str, ScalePlan] = {}
        self._stopped = threading.Event()
        self._threads = []

    # -- CR plumbing ----------------------------------------------------
    def _cr_path(self, plural: str, name: str = "",
                 subresource: str = "") -> str:
        path = (f"/apis/{GROUP}/{VERSION}/namespaces/{self._namespace}"
                f"/{plural}")
        if name:
            path += f"/{name}"
        if subresource:
            path += f"/{subresource}"
        return path

    def _patch_status(self, plural: str, name: str,
                      status: Dict[str, Any]) -> None:
        try:
            self._client.api.request(
                "PATCH", self._cr_path(plural, name, "status"),
                {"status": status})
        except Exception as e:  # noqa: BLE001 — status sync is advisory
            logger.warning("status patch %s/%s failed: %s", plural, name, e)

    # -- job lifecycle ----------------------------------------------------
    def ensure_job(self, job: ElasticJob) -> ElasticJobController:
        controller = self._controllers.get(job.name)
        if controller is not None:
            self._backends[job.name].job = job
            controller.suspended = job.spec.suspend
            return controller
        backend = K8sJobCluster(job, self._client)
        controller = ElasticJobController(job.name, backend)
        controller.suspended = job.spec.suspend
        self._backends[job.name] = backend
        self._controllers[job.name] = controller
        logger.info("tracking ElasticJob %s", job.name)
        return controller

    def forget_job(self, name: str) -> None:
        controller = self._controllers.pop(name, None)
        self._backends.pop(name, None)
        self._patched_phase.pop(name, None)
        if controller is not None:
            controller.stop()
            logger.info("dropped ElasticJob %s", name)

    def handle_job_event(self, event: Dict[str, Any]) -> None:
        obj = event.get("object", {})
        job = ElasticJob.from_manifest(obj)
        if not job.name:
            return
        if event.get("type") == "DELETED":
            self.forget_job(job.name)
        else:                              # ADDED / MODIFIED
            self.ensure_job(job)

    def handle_scaleplan_event(self, event: Dict[str, Any]) -> None:
        """Relay a manual ScalePlan to the owner job's master
        (reference: ScalePlanReconciler + elasticjob_scaler.py).
        Idempotent: plans already phase=Relayed (our own status patch
        echoes back as MODIFIED, and watch reconnects replay existing
        plans) are skipped; plans whose owner isn't tracked yet are
        parked and retried — the two watch streams race."""
        plan = ScalePlan.from_manifest(event.get("object", {}))
        if not plan.name:
            return    # ERROR/Status watch events carry no object name
        if event.get("type") == "DELETED":
            self._orphan_plans.pop(plan.name, None)
            # a later re-created plan with the same name is a NEW request
            self._relayed_plans.discard(plan.name)
            return
        if plan.phase == "Relayed" or plan.name in self._relayed_plans:
            return
        self._relay_plan(plan)

    def _relay_plan(self, plan: ScalePlan) -> None:
        controller = self._controllers.get(plan.spec.owner_job)
        if controller is None:
            logger.warning("ScalePlan %s: owner job %r not tracked yet; "
                           "parked", plan.name, plan.spec.owner_job)
            self._orphan_plans[plan.name] = plan
            return
        self._orphan_plans.pop(plan.name, None)
        for node_type, count in plan.spec.replica_resource_specs.items():
            controller.submit_scale_plan(node_type, count)
        self._relayed_plans.add(plan.name)
        self._patch_status(SCALEPLAN_PLURAL, plan.name,
                           {"phase": "Relayed"})

    # -- reconcile --------------------------------------------------------
    def reconcile_all(self) -> None:
        from dlrover_tpu.operator.controller import PHASE_NAMES

        for plan in list(self._orphan_plans.values()):
            self._relay_plan(plan)
        for name, controller in list(self._controllers.items()):
            try:
                controller.reconcile_once()
                phase = PHASE_NAMES[controller.phase]
                # status patch only on transition, not every tick
                if self._patched_phase.get(name) != phase:
                    self._patch_status(ELASTICJOB_PLURAL, name,
                                       {"phase": phase})
                    self._patched_phase[name] = phase
            except Exception as e:  # noqa: BLE001 — operator must survive
                logger.error("reconcile %s failed: %s", name, e)

    def list_existing_jobs(self) -> None:
        """Adopt CRs that existed before the operator started."""
        try:
            items = self._client.api.request(
                "GET", self._cr_path(ELASTICJOB_PLURAL)).get("items", [])
        except Exception as e:  # noqa: BLE001
            logger.warning("initial ElasticJob list failed: %s", e)
            return
        for obj in items:
            self.ensure_job(ElasticJob.from_manifest(obj))

    # -- loops ------------------------------------------------------------
    def _watch_loop(self, plural: str, handler) -> None:
        while not self._stopped.is_set():
            try:
                for event in self._client.api.stream(
                        self._cr_path(plural) + "?watch=true"):
                    handler(event)
                    if self._stopped.is_set():
                        break
            except Exception as e:  # noqa: BLE001 — reconnect on drop
                if not self._stopped.is_set():
                    logger.warning("%s watch dropped: %s; reconnecting",
                                   plural, e)
                    self._stopped.wait(1.0)

    def _reconcile_loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            self.reconcile_all()

    def start(self) -> None:
        self.list_existing_jobs()
        self._threads = [
            threading.Thread(
                target=self._watch_loop,
                args=(ELASTICJOB_PLURAL, self.handle_job_event),
                daemon=True, name="watch-elasticjobs"),
            threading.Thread(
                target=self._watch_loop,
                args=(SCALEPLAN_PLURAL, self.handle_scaleplan_event),
                daemon=True, name="watch-scaleplans"),
            threading.Thread(target=self._reconcile_loop, daemon=True,
                             name="operator-reconcile"),
        ]
        for thread in self._threads:
            thread.start()

    def stop(self) -> None:
        self._stopped.set()
        for controller in self._controllers.values():
            controller.stop()


def main(argv=None) -> int:
    """`python -m dlrover_tpu.operator.k8s_operator` — the operator
    process entry (reference: the Go operator binary)."""
    import argparse
    import time

    parser = argparse.ArgumentParser("dlrover-tpu-operator")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--interval", type=float, default=2.0)
    ns = parser.parse_args(argv)
    operator = K8sElasticJobOperator(ns.namespace,
                                     reconcile_interval_s=ns.interval)
    operator.start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        operator.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
