"""ctypes bridge to the native reconciler (native/reconciler.cpp) with a
behavior-identical Python fallback."""

from __future__ import annotations

import ctypes
import dataclasses
from typing import List

from dlrover_tpu.native_build import load_native


class PodPhase:
    ABSENT = 0
    PENDING = 1
    RUNNING = 2
    SUCCEEDED = 3
    FAILED = 4


class JobPhase:
    CREATED = 0
    PENDING = 1
    RUNNING = 2
    SUCCEEDED = 3
    FAILED = 4
    SCALING = 5


class ActionKind:
    NONE = 0
    CREATE_MASTER = 1
    RELAUNCH_MASTER = 2
    SET_PHASE = 3
    RELAY_SCALE_PLAN = 4
    FAIL_JOB = 5


@dataclasses.dataclass
class JobObserved:
    job_phase: int = JobPhase.CREATED
    master_phase: int = PodPhase.ABSENT
    master_restarts: int = 0
    max_master_restarts: int = 3
    suspended: bool = False
    pending_scale_plan: bool = False
    workers_total: int = 0
    workers_running: int = 0
    workers_succeeded: int = 0
    workers_failed_unrecoverable: int = 0


@dataclasses.dataclass
class Action:
    kind: int
    arg: int = 0


class _CJobObserved(ctypes.Structure):
    _fields_ = [(name, ctypes.c_int32) for name in (
        "job_phase", "master_phase", "master_restarts",
        "max_master_restarts", "suspended", "pending_scale_plan",
        "workers_total", "workers_running", "workers_succeeded",
        "workers_failed_unrecoverable")]


class _CAction(ctypes.Structure):
    _fields_ = [("kind", ctypes.c_int32), ("arg", ctypes.c_int32)]


def _native_reconcile(job: JobObserved) -> List[Action]:
    lib = load_native()
    assert lib is not None
    lib.reconcile_elastic_job.restype = ctypes.c_int32
    lib.reconcile_elastic_job.argtypes = [
        ctypes.POINTER(_CJobObserved), ctypes.POINTER(_CAction),
        ctypes.c_int32]
    c_job = _CJobObserved(
        job.job_phase, job.master_phase, job.master_restarts,
        job.max_master_restarts, int(job.suspended),
        int(job.pending_scale_plan), job.workers_total,
        job.workers_running, job.workers_succeeded,
        job.workers_failed_unrecoverable)
    out = (_CAction * 8)()
    n = lib.reconcile_elastic_job(ctypes.byref(c_job), out, 8)
    return [Action(out[i].kind, out[i].arg) for i in range(n)]


def _python_reconcile(job: JobObserved) -> List[Action]:
    """Fallback mirroring native/reconciler.cpp exactly."""
    actions: List[Action] = []
    if job.suspended:
        return actions
    if job.job_phase in (JobPhase.SUCCEEDED, JobPhase.FAILED):
        return actions
    mp = job.master_phase
    if mp == PodPhase.ABSENT:
        actions.append(Action(ActionKind.CREATE_MASTER))
        if job.job_phase != JobPhase.PENDING:
            actions.append(Action(ActionKind.SET_PHASE, JobPhase.PENDING))
    elif mp == PodPhase.PENDING:
        if job.job_phase != JobPhase.PENDING:
            actions.append(Action(ActionKind.SET_PHASE, JobPhase.PENDING))
    elif mp == PodPhase.RUNNING:
        if job.job_phase != JobPhase.RUNNING:
            actions.append(Action(ActionKind.SET_PHASE, JobPhase.RUNNING))
        if job.pending_scale_plan:
            actions.append(Action(ActionKind.RELAY_SCALE_PLAN))
    elif mp == PodPhase.SUCCEEDED:
        actions.append(Action(ActionKind.SET_PHASE, JobPhase.SUCCEEDED))
    elif mp == PodPhase.FAILED:
        if job.master_restarts < job.max_master_restarts:
            actions.append(Action(ActionKind.RELAUNCH_MASTER,
                                  job.master_restarts + 1))
        else:
            actions.append(Action(ActionKind.FAIL_JOB, 1))
            actions.append(Action(ActionKind.SET_PHASE, JobPhase.FAILED))
    if mp == PodPhase.ABSENT and job.workers_total > 0:
        if job.workers_succeeded == job.workers_total:
            actions.append(Action(ActionKind.SET_PHASE,
                                  JobPhase.SUCCEEDED))
        elif job.workers_failed_unrecoverable == job.workers_total:
            actions.append(Action(ActionKind.FAIL_JOB, 2))
            actions.append(Action(ActionKind.SET_PHASE, JobPhase.FAILED))
    return actions


def reconcile(job: JobObserved) -> List[Action]:
    if load_native() is not None:
        return _native_reconcile(job)
    return _python_reconcile(job)
