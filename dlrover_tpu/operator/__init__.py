"""ElasticJob operator: reconcile loop + master-pod lifecycle.

Capability parity: the Go operator (dlrover/go/operator/ — ElasticJob/
ScalePlan CRDs elasticjob_types.go:29-123, Reconcile
elasticjob_controller.go:85, master pod master/master.go:53-162). The
decision core is native C++ (native/reconciler.cpp) behind ctypes; this
package is the actuation shell (k8s REST or the in-memory LocalCluster).
"""

from dlrover_tpu.operator.native import Action, ActionKind, JobObserved, reconcile
from dlrover_tpu.operator.controller import ElasticJobController

__all__ = ["Action", "ActionKind", "JobObserved", "reconcile",
           "ElasticJobController"]
