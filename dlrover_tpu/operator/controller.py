"""Operator actuation shell: observe → native reconcile → act.

Capability parity: ElasticJobReconciler (elasticjob_controller.go:85) +
master.Manager (master/master.go:53-162: master pod/service construction,
DLROVER_MASTER_ADDR injection) + ScalePlanReconciler relay. Runs against
the in-memory LocalCluster (tests/standalone) or the k8s REST client.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.operator.native import (
    Action,
    ActionKind,
    JobObserved,
    JobPhase,
    PodPhase,
    reconcile,
)

_POD_STATUS_TO_PHASE = {
    NodeStatus.PENDING: PodPhase.PENDING,
    NodeStatus.RUNNING: PodPhase.RUNNING,
    NodeStatus.SUCCEEDED: PodPhase.SUCCEEDED,
    NodeStatus.FAILED: PodPhase.FAILED,
    NodeStatus.BREAKDOWN: PodPhase.FAILED,
}

PHASE_NAMES = {
    JobPhase.CREATED: "Created",
    JobPhase.PENDING: "Pending",
    JobPhase.RUNNING: "Running",
    JobPhase.SUCCEEDED: "Succeeded",
    JobPhase.FAILED: "Failed",
    JobPhase.SCALING: "Scaling",
}


class ElasticJobController:
    """One controller per job against the LocalCluster backend (the k8s
    shell wires the same reconcile core to K8sClient CRUD)."""

    def __init__(
        self,
        job_name: str,
        cluster,                       # LocalCluster
        master_factory=None,           # () -> started master; returns addr
        max_master_restarts: int = 3,
        interval_s: float = 1.0,
    ):
        self._job_name = job_name
        self._cluster = cluster
        self._master_factory = master_factory
        self._interval_s = interval_s
        self.phase = JobPhase.CREATED
        self.master_restarts = 0
        self.max_master_restarts = max_master_restarts
        self.master_addr = ""
        # node type -> requested count; a ScalePlan may scale several
        # node groups at once (scaleplan_types.go replicaResourceSpecs)
        self.pending_scale_plans: Dict[str, int] = {}
        self.suspended = False
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._master_handle = None
        # guards the reconcile state shared between the controller
        # thread and CR-entry callers (submit_scale_plan/observe/tests);
        # never held across a cluster call, master launch, or relay RPC
        self._lock = threading.Lock()

    # -- observation ---------------------------------------------------
    def observe(self) -> JobObserved:
        master_phase = PodPhase.ABSENT
        for pod in self._cluster.list_pods(NodeType.MASTER):
            master_phase = _POD_STATUS_TO_PHASE.get(pod.status,
                                                    PodPhase.ABSENT)
        workers = self._cluster.list_pods(NodeType.WORKER)
        with self._lock:
            job_phase = self.phase
            master_restarts = self.master_restarts
            suspended = self.suspended
            pending = bool(self.pending_scale_plans)
        return JobObserved(
            job_phase=job_phase,
            master_phase=master_phase,
            master_restarts=master_restarts,
            max_master_restarts=self.max_master_restarts,
            suspended=suspended,
            pending_scale_plan=pending,
            workers_total=len(workers),
            workers_running=sum(
                1 for p in workers if p.status == NodeStatus.RUNNING),
            workers_succeeded=sum(
                1 for p in workers if p.status == NodeStatus.SUCCEEDED),
            workers_failed_unrecoverable=sum(
                1 for p in workers if p.status == NodeStatus.FAILED),
        )

    # -- actuation -------------------------------------------------------
    def _act(self, action: Action) -> None:
        if action.kind == ActionKind.CREATE_MASTER:
            self._create_master()
        elif action.kind == ActionKind.RELAUNCH_MASTER:
            with self._lock:
                self.master_restarts = action.arg
            logger.warning("relaunching master (%d/%d)",
                           action.arg, self.max_master_restarts)
            for pod in self._cluster.list_pods(NodeType.MASTER):
                self._cluster.delete_pod(pod.name)
            self._create_master()
        elif action.kind == ActionKind.SET_PHASE:
            with self._lock:
                changed = self.phase != action.arg
                if changed:
                    self.phase = action.arg
            if changed:
                logger.info("job %s phase -> %s", self._job_name,
                            PHASE_NAMES[action.arg])
        elif action.kind == ActionKind.RELAY_SCALE_PLAN:
            self._relay_scale_plan()
        elif action.kind == ActionKind.FAIL_JOB:
            logger.error("job %s failed (reason code %d)", self._job_name,
                         action.arg)

    def _create_master(self) -> None:
        with self._lock:
            ordinal = self.master_restarts
        if hasattr(self._cluster, "create_master"):
            # k8s backend: master runs as a pod behind a stable service
            # (reference: master/master.go:53-162). The pod name carries
            # the restart ordinal: a relaunch must not collide with the
            # old pod's asynchronous (graceful) deletion.
            addr = self._cluster.create_master(ordinal=ordinal)
            with self._lock:
                self.master_addr = addr
            return
        from dlrover_tpu.scheduler.local import PodRecord

        if self._master_factory is not None:
            # the factory launches a full master: keep the lock out of
            # that call and publish handle + addr once it returns
            handle, addr = self._master_factory()
            with self._lock:
                self._master_handle = handle
                self.master_addr = addr
        else:
            with self._lock:
                addr = self.master_addr
        self._cluster.create_pod(PodRecord(
            name=f"{self._job_name}-master-0",
            node_type=NodeType.MASTER,
            node_id=0,
            rank_index=0,
            env={"DLROVER_TPU_MASTER_ADDR": addr},
        ))

    def _relay_scale_plan(self) -> None:
        with self._lock:
            plans, self.pending_scale_plans = self.pending_scale_plans, {}
            addr = self.master_addr
        if not plans or not addr:
            return
        from dlrover_tpu.agent.master_client import MasterClient

        try:
            client = MasterClient(addr, node_id=-1)
            try:
                for node_type, count in list(plans.items()):
                    client._report(msg.ScaleRequest(node_type=node_type,
                                                    count=count))
                    logger.info("relayed scale plan %s=%d to master",
                                node_type, count)
                    del plans[node_type]
            finally:
                client.close()
        except Exception as e:  # noqa: BLE001
            logger.warning("scale-plan relay failed: %s; requeued", e)
            # not-yet-sent entries go back; a newer request wins
            with self._lock:
                for node_type, count in plans.items():
                    self.pending_scale_plans.setdefault(node_type, count)

    def submit_scale_plan(self, node_type: str, count: int) -> None:
        """The ScalePlan-CR entry (reference: ScalePlanReconciler)."""
        with self._lock:
            self.pending_scale_plans[node_type] = count

    # -- loop ------------------------------------------------------------
    def reconcile_once(self) -> JobObserved:
        observed = self.observe()
        for action in reconcile(observed):
            self._act(action)
        return observed

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="elasticjob-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.reconcile_once()
            except Exception as e:  # noqa: BLE001 - controller must survive
                logger.error("reconcile failed: %s", e)
