"""ElasticJob / ScalePlan custom-resource schemas.

Capability parity: the operator API types —
`dlrover/go/operator/api/v1alpha1/elasticjob_types.go:29-123` (ElasticJobSpec:
distributionStrategy, resourceLimits, optimizeMode, brainService,
enableElasticScheduling, enableDynamicSharding, replicaSpecs, suspend) and
`scaleplan_types.go:29-121` (ScaleSpec: replicaResourceSpecs, createPods,
removePods, migratePods, psHosts, manualScaling) — as plain dataclasses with
manifest (de)serialization. The YAML CRD definitions live in `manifests/`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

from dlrover_tpu.common.node import NodeResource

GROUP = "elastic.dlrover-tpu.org"
VERSION = "v1alpha1"
ELASTICJOB_PLURAL = "elasticjobs"
SCALEPLAN_PLURAL = "scaleplans"

_MEMORY_SUFFIXES = {
    "Ki": 1 / 1024, "Mi": 1, "Gi": 1024, "Ti": 1024 * 1024,
    "K": 1e3 / (1 << 20), "M": 1e6 / (1 << 20), "G": 1e9 / (1 << 20),
    "T": 1e12 / (1 << 20),
}


def parse_cpu(value: Any) -> float:
    """k8s cpu quantity → cores ('500m' → 0.5, '8' → 8.0)."""
    text = str(value or 0).strip()
    if not text:
        return 0.0
    if text.endswith("m"):
        return float(text[:-1]) / 1000.0
    return float(text)


def parse_memory_mb(value: Any) -> float:
    """k8s memory quantity → MiB ('32Gi' → 32768, '1G' → ~953.7,
    plain numbers are bytes)."""
    text = str(value or 0).strip()
    if not text:
        return 0.0
    for suffix, factor in sorted(_MEMORY_SUFFIXES.items(),
                                 key=lambda kv: -len(kv[0])):
        if text.endswith(suffix):
            return float(text[:-len(suffix)]) * factor
    return float(text) / (1 << 20)


@dataclasses.dataclass
class ReplicaSpec:
    """One node group (reference: ReplicaSpec in elasticjob_types.go —
    replicas + pod template + RestartCount/Priority extensions)."""

    replicas: int = 0
    min_replicas: int = 0
    max_replicas: int = 0
    restart_count: int = 3
    priority: str = ""
    image: str = ""
    command: str = ""
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)
    tpu_topology: str = ""

    @classmethod
    def from_manifest(cls, spec: Dict[str, Any]) -> "ReplicaSpec":
        template = spec.get("template", {})
        pod_spec = template.get("spec", {})
        containers = pod_spec.get("containers", [{}])
        main = containers[0] if containers else {}
        limits = (main.get("resources", {}) or {}).get("limits", {}) or {}
        command = main.get("command") or []
        if isinstance(command, list):
            command = " ".join(command[2:] if command[:2] ==
                               ["/bin/sh", "-c"] else command)
        selector = pod_spec.get("nodeSelector", {}) or {}
        return cls(
            replicas=int(spec.get("replicas", 0)),
            min_replicas=int(spec.get("minReplicas", 0)),
            max_replicas=int(spec.get("maxReplicas", 0)),
            restart_count=int(spec.get("restartCount", 3)),
            priority=spec.get("priority", ""),
            image=main.get("image", ""),
            command=command,
            resource=NodeResource(
                cpu=parse_cpu(limits.get("cpu", 0)),
                memory_mb=parse_memory_mb(limits.get("memory", 0)),
                chips=int(limits.get("google.com/tpu", 0) or 0),
                chip_type=selector.get(
                    "cloud.google.com/gke-tpu-accelerator", ""),
            ),
            tpu_topology=selector.get(
                "cloud.google.com/gke-tpu-topology", ""),
        )

    def to_manifest(self) -> Dict[str, Any]:
        from dlrover_tpu.scheduler.kubernetes import (
            resource_to_limits,
            shell_command,
            tpu_node_selector,
        )

        limits = resource_to_limits(self.resource)
        selector = tpu_node_selector(self.resource.chip_type,
                                     self.tpu_topology)
        spec: Dict[str, Any] = {
            "replicas": self.replicas,
            "restartCount": self.restart_count,
            "template": {"spec": {
                "containers": [{
                    "name": "main",
                    "image": self.image,
                    "command": shell_command(self.command),
                    "resources": {"limits": limits},
                }],
                "nodeSelector": selector or None,
            }},
        }
        if self.min_replicas:
            spec["minReplicas"] = self.min_replicas
        if self.max_replicas:
            spec["maxReplicas"] = self.max_replicas
        if self.priority:
            spec["priority"] = self.priority
        container = spec["template"]["spec"]["containers"][0]
        spec["template"]["spec"]["containers"] = [
            {k: v for k, v in container.items() if v is not None}]
        spec["template"]["spec"] = {
            k: v for k, v in spec["template"]["spec"].items()
            if v is not None}
        return spec


@dataclasses.dataclass
class ElasticJobSpec:
    """Reference: ElasticJobSpec elasticjob_types.go:29-123."""

    distribution_strategy: str = "AllreduceStrategy"
    optimize_mode: str = "single-job"       # manual | single-job | cluster
    brain_service: str = ""
    enable_elastic_scheduling: bool = True
    enable_dynamic_sharding: bool = True
    suspend: bool = False
    resource_limits: Dict[str, str] = dataclasses.field(default_factory=dict)
    replica_specs: Dict[str, ReplicaSpec] = dataclasses.field(
        default_factory=dict)

    @classmethod
    def from_manifest(cls, spec: Dict[str, Any]) -> "ElasticJobSpec":
        return cls(
            distribution_strategy=spec.get("distributionStrategy",
                                           "AllreduceStrategy"),
            optimize_mode=spec.get("optimizeMode", "single-job"),
            brain_service=spec.get("brainService", ""),
            enable_elastic_scheduling=bool(
                spec.get("enableElasticScheduling", True)),
            enable_dynamic_sharding=bool(
                spec.get("enableDynamicSharding", True)),
            suspend=bool(spec.get("suspend", False)),
            resource_limits=dict(spec.get("resourceLimits", {}) or {}),
            replica_specs={
                name: ReplicaSpec.from_manifest(rs)
                for name, rs in (spec.get("replicaSpecs", {}) or {}).items()
            },
        )

    def to_manifest(self) -> Dict[str, Any]:
        return {
            "distributionStrategy": self.distribution_strategy,
            "optimizeMode": self.optimize_mode,
            "brainService": self.brain_service,
            "enableElasticScheduling": self.enable_elastic_scheduling,
            "enableDynamicSharding": self.enable_dynamic_sharding,
            "suspend": self.suspend,
            "resourceLimits": self.resource_limits,
            "replicaSpecs": {name: rs.to_manifest()
                             for name, rs in self.replica_specs.items()},
        }


@dataclasses.dataclass
class ElasticJob:
    name: str
    namespace: str = "default"
    uid: str = ""
    spec: ElasticJobSpec = dataclasses.field(default_factory=ElasticJobSpec)
    phase: str = "Created"

    @classmethod
    def from_manifest(cls, obj: Dict[str, Any]) -> "ElasticJob":
        meta = obj.get("metadata", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            spec=ElasticJobSpec.from_manifest(obj.get("spec", {})),
            phase=(obj.get("status", {}) or {}).get("phase", "Created"),
        )

    def to_manifest(self) -> Dict[str, Any]:
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "ElasticJob",
            "metadata": {"name": self.name, "namespace": self.namespace,
                         **({"uid": self.uid} if self.uid else {})},
            "spec": self.spec.to_manifest(),
            "status": {"phase": self.phase},
        }

    def to_job_args(self):
        """Parsed CR → the master's JobArgs (reference:
        K8sJobArgs.initilize, scheduler/kubernetes.py:360-441 parses the
        CRD into NodeArgs). This is how the k8s-launched master learns
        the job's replica specs."""
        from dlrover_tpu.common.constants import NodeType, PlatformType
        from dlrover_tpu.common.node import NodeGroupResource
        from dlrover_tpu.scheduler.job import JobArgs, NodeArgs

        args = JobArgs(platform=PlatformType.KUBERNETES,
                       namespace=self.namespace, job_name=self.name)
        args.distribution_strategy = self.spec.distribution_strategy
        args.optimize_mode = self.spec.optimize_mode
        args.enable_dynamic_sharding = self.spec.enable_dynamic_sharding
        args.enable_elastic_scheduling = (
            self.spec.enable_elastic_scheduling)
        for node_type, replica in self.spec.replica_specs.items():
            if node_type == "master":
                continue
            args.node_args[node_type] = NodeArgs(
                group_resource=NodeGroupResource(
                    count=replica.replicas,
                    node_resource=replica.resource,
                ),
                restart_count=replica.restart_count,
                critical=node_type == NodeType.PS,
                min_count=replica.min_replicas,
                max_count=replica.max_replicas,
            )
        worker = self.spec.replica_specs.get(NodeType.WORKER)
        if worker is not None:
            args.image = worker.image
            args.command = worker.command
            args.tpu_topology = worker.tpu_topology
        return args

    def owner_reference(self) -> Dict[str, Any]:
        """Pods owned by the job get garbage-collected with it
        (reference: master/master.go pod construction)."""
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "ElasticJob",
            "name": self.name,
            "uid": self.uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }


@dataclasses.dataclass
class ScaleSpec:
    """Reference: ScaleSpec scaleplan_types.go:29-121 (replica resource
    specs, explicit create/remove pod lists, migrate, psHosts, manual)."""

    owner_job: str = ""
    replica_resource_specs: Dict[str, int] = dataclasses.field(
        default_factory=dict)            # node type -> replicas
    create_pods: List[str] = dataclasses.field(default_factory=list)
    remove_pods: List[str] = dataclasses.field(default_factory=list)
    ps_hosts: List[str] = dataclasses.field(default_factory=list)
    manual_scaling: bool = True

    @classmethod
    def from_manifest(cls, spec: Dict[str, Any]) -> "ScaleSpec":
        replica_specs = {}
        for name, rs in (spec.get("replicaResourceSpecs", {}) or {}).items():
            if isinstance(rs, dict):
                if "replicas" not in rs:
                    continue    # resource-only entry: nothing to scale
                replica_specs[name] = int(rs["replicas"])
            else:
                replica_specs[name] = int(rs)
        return cls(
            owner_job=spec.get("ownerJob", ""),
            replica_resource_specs=replica_specs,
            create_pods=[p.get("name", "") if isinstance(p, dict) else p
                         for p in spec.get("createPods", []) or []],
            remove_pods=[p.get("name", "") if isinstance(p, dict) else p
                         for p in spec.get("removePods", []) or []],
            ps_hosts=list(spec.get("psHosts", []) or []),
            manual_scaling=bool(spec.get("manualScaling", True)),
        )


@dataclasses.dataclass
class ScalePlan:
    name: str
    namespace: str = "default"
    spec: ScaleSpec = dataclasses.field(default_factory=ScaleSpec)
    phase: str = "Pending"

    @classmethod
    def from_manifest(cls, obj: Dict[str, Any]) -> "ScalePlan":
        meta = obj.get("metadata", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            spec=ScaleSpec.from_manifest(obj.get("spec", {})),
            phase=(obj.get("status", {}) or {}).get("phase", "Pending"),
        )
