"""Brain optimizer algorithms.

Capability parity: dlrover/go/brain/pkg/optimizer/implementation/
optalgorithm/ — each algorithm maps (stage, job config, historical
metrics) → resource plan:
- `optimize_job_create_resource`: cold-start worker shape from similar
  completed jobs (reference: optimize_job_ps_create_resource.go reframed
  for TPU hosts).
- `optimize_job_oom_resource`: memory bump beyond what the local plan does,
  informed by the job's own peak usage
  (optimize_job_worker_create_oom_resource.go).
- `optimize_job_hot_host`: input-bound host detection from persisted
  runtime stats (optimize_job_hot_ps_resource.go).
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List, Optional

from dlrover_tpu.brain.datastore import MetricsStore

Plan = Dict[str, Any]


def optimize_job_create_resource(store: MetricsStore,
                                 job_name: str,
                                 config: Optional[Dict] = None) -> Plan:
    """Cold-start plan: median worker shape of recently-completed jobs
    whose model size is within 2× of this job's (if model info known)."""
    config = config or {}
    history = store.completed_jobs()
    if not history:
        return {}
    param_count = float(config.get("param_count", 0))
    counts: List[int] = []
    cpus: List[float] = []
    mems: List[float] = []
    chips: List[int] = []
    for name in history:
        model = store.query(job_name=name, record_type="model", limit=1)
        if param_count and model:
            other = float(model[0]["payload"].get("param_count", 0))
            if other and not (0.5 <= other / param_count <= 2.0):
                continue
        meta = store.query(job_name=name, record_type="job_meta", limit=1)
        if not meta:
            continue
        payload = meta[0]["payload"]
        if payload.get("worker_count"):
            counts.append(int(payload["worker_count"]))
        if payload.get("cpu"):
            cpus.append(float(payload["cpu"]))
        if payload.get("memory_mb"):
            mems.append(float(payload["memory_mb"]))
        if payload.get("chips"):
            chips.append(int(payload["chips"]))
    if not counts:
        return {}
    plan: Plan = {"node_group_resources": {"worker": {
        "count": int(statistics.median(counts)),
    }}}
    resource = plan["node_group_resources"]["worker"]
    if cpus:
        resource["cpu"] = statistics.median(cpus)
    if mems:
        resource["memory_mb"] = statistics.median(mems)
    if chips:
        resource["chips"] = int(statistics.median(chips))
    return plan


def optimize_job_oom_resource(store: MetricsStore, job_name: str,
                              config: Optional[Dict] = None) -> Plan:
    """OOM recovery: size memory to observed peak × 1.8 (at least 1.5× the
    current config)."""
    config = config or {}
    current = float(config.get("memory_mb", 0))
    peak = 0.0
    for record in store.query(job_name=job_name, record_type="runtime",
                              limit=200):
        peak = max(peak, float(record["payload"].get("peak_memory_mb", 0)))
    target = max(peak * 1.8, current * 1.5)
    if target <= 0:
        return {}
    return {"node_group_resources": {"worker": {
        "count": 0, "memory_mb": target,
    }}}


def optimize_job_hot_host(store: MetricsStore, job_name: str,
                          config: Optional[Dict] = None) -> Plan:
    """Hosts with pegged CPU and idle chips → more dataloader parallelism
    (and more host CPU if spec allows)."""
    from dlrover_tpu.master.resource.local_optimizer import (
        HOT_HOST_CPU_PCT,
        IDLE_CHIP_DUTY_PCT,
    )

    hot = 0
    total = 0
    for record in store.query(job_name=job_name, record_type="runtime",
                              limit=50):
        payload = record["payload"]
        if "cpu_percent" not in payload:
            continue
        total += 1
        if (payload.get("cpu_percent", 0) >= HOT_HOST_CPU_PCT
                and payload.get("chip_duty_cycle_pct", 100)
                < IDLE_CHIP_DUTY_PCT):
            hot += 1
    if total and hot / total >= 0.3:
        return {"dataloader_workers": 2}
    return {}


ALGORITHMS = {
    "job-create": optimize_job_create_resource,
    "oom-recovery": optimize_job_oom_resource,
    "running": optimize_job_hot_host,
}


def run_algorithm(stage: str, store: MetricsStore, job_name: str,
                  config: Optional[Dict] = None) -> Plan:
    algo = ALGORITHMS.get(stage)
    if algo is None:
        return {}
    return algo(store, job_name, config)
