"""Goodput-optimal fleet controller: close the diagnosis→actuation loop.

Five PRs of telemetry (goodput ledger, steptrace critical path, plan
calibration, speed monitor, diagnosis chain) MEASURE everything and act
on nothing. This module is the actuator: a master-side control loop
that, on a fixed cadence, decides one of three things —

- **claim** an offered preemptible slice: the marginal predicted
  productive time the offer would contribute (its remaining lifetime ×
  the fleet's measured windowed goodput fraction) must beat the
  join+re-plan cost — estimated from the ledger's own recent
  elasticity incarnations — by ``autoscale_claim_margin``;
- **shed** the slowest slice: the steptrace summary names one rank as
  dominating the fleet's critical path AND the cross-slice (DCN) wait
  fraction exceeds ``autoscale_shed_wait_fraction`` — the fleet is
  paying more waiting for that slice than it would pay re-planning
  without it;
- **hold**: anything else, and every candidate blocked by a guardrail
  (hysteresis, cooldown, hourly rate limit, quarantine, an open
  watchdog window). Holds with a live candidate are recorded —
  "we saw it and deliberately did nothing" is a decision.

Every actuation goes through the EXISTING machinery: a shed is a
synthetic advance-notice drain (the servicer's slice-unit drain chain,
PR 5), a claim is granted by the :class:`CapacityProvider` (whose local
implementation the chaos grammar and test harnesses drive) and the new
slice joins through ordinary rendezvous + one-round re-plan (PR 8/9).
Each decision lands as a diagnosis report, a flight event, and — for
actuations — a ledger incarnation priced under the ``autoscale``
elasticity kind.

The **rollback watchdog** guards every actuation: the windowed goodput
fraction at actuation time is the baseline; ``autoscale_rollback_window_s``
later the window is re-read, and a drop beyond
``autoscale_rollback_drop_fraction`` reverts the actuation (a bad claim
sheds the slice it claimed) and quarantines that decision CLASS with a
backoff that doubles per consecutive rollback (capped 8×). A market
revocation of a slice under watch cancels the watch without penalty —
the market changing its mind is not evidence the claim was wrong.

Threading: ``evaluate_once`` runs serialized on the controller loop (or
a test caller); shared state is guarded by ``self._lock``; registry and
flight-recorder operations happen OUTSIDE the lock. The clock is
injectable so guardrail tests run on a fake clock. stdlib-only.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.log import default_logger as logger

_DECISION_RING = 128       # decisions retained in memory
_PERSISTED_DECISIONS = 64  # newest decisions carried in state snapshots
# join+re-plan price before the ledger has observed one (a deliberately
# conservative figure: one rendezvous round + restore at small scale)
_DEFAULT_ACTUATION_COST_S = 45.0
_COST_SAMPLE_INCARNATIONS = 4   # recent incarnations averaged for cost
_QUARANTINE_MAX_MULTIPLIER = 8  # backoff cap: 8 × base quarantine
# an offer with no TTL is priced over this assumed lifetime
_DEFAULT_OFFER_LIFETIME_S = 300.0


@dataclasses.dataclass
class CapacityOffer:
    """One open offer of preemptible capacity: ``slices`` whole slices,
    valid for ``ttl_s`` from ``offered_at`` (0 = until revoked)."""

    offer_id: int
    slices: int = 1
    ttl_s: float = 0.0
    offered_at: float = 0.0
    step: int = -1

    def remaining_s(self, now: float) -> float:
        if self.ttl_s <= 0.0:
            return _DEFAULT_OFFER_LIFETIME_S
        return max(0.0, self.ttl_s - (now - self.offered_at))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class CapacityProvider:
    """The spot-market surface the controller sees. Implementations:
    :class:`LocalCapacityProvider` (chaos/test-driven, in-process) now;
    a cloud quota/reservation API adapter is the intended production
    shape — the controller only ever calls these three methods."""

    def open_offers(self) -> List[CapacityOffer]:
        raise NotImplementedError

    def claim(self, offer_id: int) -> Optional[List[int]]:
        """Claim an open offer. Returns the granted slice ids (what the
        rollback path would have to shed), None if the offer is gone."""
        raise NotImplementedError

    def on_revoke(self, fn: Callable[[int, float], None]) -> None:
        """Register the revocation listener (slice_id, grace_s)."""
        raise NotImplementedError


class LocalCapacityProvider(CapacityProvider):
    """In-process spot market: offers arrive from the chaos grammar
    (``offer:slice:+k@step[:ttl]`` → ``ChaosInjector.offer_fn``) or a
    test/bench harness calling :meth:`offer` directly; a claim is
    granted by calling ``grant_fn`` (the harness starts the new slice's
    agents and returns their slice ids); revocations
    (``revoke:slice:S@step[:grace]``) notify the registered listener —
    the worker-side preemption notice fires separately through the
    PR 5 drain path, this hook only keeps the controller's books."""

    def __init__(self, now_fn: Callable[[], float] = time.time):
        self._now = now_fn
        self._lock = threading.Lock()
        self._offers: Dict[int, CapacityOffer] = {}
        self._next_offer_id = 1
        # harness hook: actually materialize the granted capacity
        # (start agents / admit joiners); returns granted slice ids
        self.grant_fn: Optional[Callable[[CapacityOffer],
                                         Optional[List[int]]]] = None
        self._revoke_listener: Optional[Callable[[int, float],
                                                 None]] = None
        self._offers_total = obs.get_registry().counter(
            "dlrover_tpu_capacity_offers_total",
            "Preemptible-capacity market events seen by the local "
            "provider", labelnames=("event",))
        obs.get_registry().gauge(
            "dlrover_tpu_capacity_offers_open",
            "Preemptible-slice offers currently open (unclaimed, "
            "unexpired)").set_function(
                lambda: float(len(self.open_offers())))

    # -- market feeds (chaos offer_fn / revoke_fn, harnesses) --------------
    def offer(self, slices: int, ttl_s: float = 0.0,
              step: int = -1) -> CapacityOffer:
        now = self._now()
        with self._lock:
            offer = CapacityOffer(
                offer_id=self._next_offer_id, slices=max(1, int(slices)),
                ttl_s=float(ttl_s), offered_at=now, step=int(step))
            self._next_offer_id += 1
            self._offers[offer.offer_id] = offer
        self._offers_total.labels(event="offered").inc()
        obs.get_flight_recorder().record_event(
            "capacity_offer", offer_id=offer.offer_id,
            slices=offer.slices, ttl_s=offer.ttl_s, step=step)
        logger.info("capacity offer #%d: +%d slice(s), ttl=%.0fs",
                    offer.offer_id, offer.slices, offer.ttl_s)
        return offer

    def revoke(self, slice_id: int, grace_s: float = 0.0,
               step: int = -1) -> None:
        with self._lock:
            listener = self._revoke_listener
        self._offers_total.labels(event="revoked").inc()
        obs.get_flight_recorder().record_event(
            "capacity_revoke", slice=slice_id, grace_s=grace_s,
            step=step)
        logger.warning("capacity revoke: slice %d departs in %.0fs",
                       slice_id, grace_s)
        if listener is not None:
            try:
                listener(slice_id, grace_s)
            except Exception:  # noqa: BLE001 — books, not the drain
                logger.exception("revoke listener failed")

    # -- the controller's surface ------------------------------------------
    def open_offers(self) -> List[CapacityOffer]:
        now = self._now()
        expired: List[int] = []
        with self._lock:
            for offer_id, offer in list(self._offers.items()):
                if offer.ttl_s > 0.0 and \
                        now - offer.offered_at > offer.ttl_s:
                    expired.append(offer_id)
                    del self._offers[offer_id]
            live = sorted(self._offers.values(),
                          key=lambda o: o.offer_id)
        for _ in expired:
            self._offers_total.labels(event="expired").inc()
        return live

    def claim(self, offer_id: int) -> Optional[List[int]]:
        with self._lock:
            offer = self._offers.pop(offer_id, None)
            grant = self.grant_fn
        if offer is None:
            return None
        self._offers_total.labels(event="claimed").inc()
        granted: Optional[List[int]] = []
        if grant is not None:
            try:
                granted = grant(offer)
            except Exception:  # noqa: BLE001 — a failed grant is an
                # empty grant; the watchdog prices the consequences
                logger.exception("capacity grant failed")
                granted = []
        return list(granted or [])

    def on_revoke(self, fn: Callable[[int, float], None]) -> None:
        with self._lock:
            self._revoke_listener = fn


class FleetController:
    """The decision loop. All collaborators are optional (evidence that
    is absent simply never produces a candidate), so unit tests build a
    controller from fakes and a fake clock."""

    def __init__(self, ledger=None, speed_monitor=None, steptrace=None,
                 plan_calibration=None, rendezvous=None, diagnosis=None,
                 provider: Optional[CapacityProvider] = None,
                 now_fn: Callable[[], float] = time.time):
        self._now = now_fn
        self._ledger = ledger
        self._speed_monitor = speed_monitor
        self._steptrace = steptrace
        self._plan_calibration = plan_calibration
        self._rendezvous = rendezvous
        self._diagnosis = diagnosis
        self._provider = provider
        # actuator hook (JobMaster): (rank, deadline_ts, reason) →
        # the servicer's slice-unit drain-notice chain
        self.shed_sink: Optional[Callable[[int, float, str],
                                          None]] = None
        # crash-consistency hook (JobMaster wires _maybe_snapshot)
        self.state_sink: Optional[Callable[[], None]] = None
        self._lock = threading.Lock()
        self._decisions: deque = deque(maxlen=_DECISION_RING)
        self._next_decision_id = 1
        # class → consecutive evaluations its candidate condition held
        # graftlint: ephemeral(evidence re-accumulates in N windows)
        self._hysteresis: Dict[str, int] = {}
        self._last_actuation_ts = 0.0
        # actuation timestamps inside the trailing hour (rate limit;
        # rollbacks are exempt — undoing damage is never rate-limited)
        self._actuation_window: deque = deque(maxlen=64)
        self._quarantine_until: Dict[str, float] = {}
        self._quarantine_level: Dict[str, int] = {}
        # the open rollback watch: {decision_id, kind, baseline,
        # until, granted} — one at a time; no new actuation while open
        self._watch: Optional[Dict[str, Any]] = None
        self._stopped = threading.Event()
        # graftlint: ephemeral(loop thread handle; start() spawns a fresh one)
        self._thread: Optional[threading.Thread] = None
        if provider is not None:
            provider.on_revoke(self._handle_revoke)
        registry = obs.get_registry()
        self._decisions_total = registry.counter(
            "dlrover_tpu_autoscale_decisions_total",
            "Fleet-controller decisions by kind (claim / shed / hold "
            "/ rollback)", labelnames=("kind",))
        registry.gauge(
            "dlrover_tpu_autoscale_quarantined_classes",
            "Decision classes currently quarantined by the rollback "
            "watchdog").set_function(self._quarantined_count)

    # -- evidence ----------------------------------------------------------
    def _window(self, ctx: Context) -> Dict[str, Any]:
        if self._ledger is None:
            return {}
        try:
            return self._ledger.window_summary(ctx.goodput_window_s)
        except Exception:  # noqa: BLE001 — evidence, not the loop
            logger.exception("goodput window read failed")
            return {}

    def _steptrace_summary(self) -> Dict[str, Any]:
        if self._steptrace is None:
            return {}
        try:
            return self._steptrace.summary()
        except Exception:  # noqa: BLE001 — evidence, not the loop
            logger.exception("steptrace summary read failed")
            return {}

    def _actuation_cost_s(self) -> float:
        """The join+re-plan price, from the ledger's own recent
        elasticity incarnations (mean badput of the newest few that
        were opened by a resize-shaped trigger). Before any evidence
        exists the conservative default applies — the first claim is
        deliberately the hardest to justify."""
        if self._ledger is None:
            return _DEFAULT_ACTUATION_COST_S
        try:
            incarnations = self._ledger.snapshot().get(
                "incarnations", [])
        except Exception:  # noqa: BLE001 — evidence, not the loop
            return _DEFAULT_ACTUATION_COST_S
        costs = [float(inc.get("badput", 0.0))
                 for inc in incarnations
                 if inc.get("reason") in ("replan", "scale",
                                          "autoscale")]
        costs = [c for c in costs if c > 0.0][-_COST_SAMPLE_INCARNATIONS:]
        if not costs:
            return _DEFAULT_ACTUATION_COST_S
        return sum(costs) / len(costs)

    # -- candidates --------------------------------------------------------
    def _claim_candidate(self, ctx: Context, now: float,
                         window: Dict[str, Any]
                         ) -> Optional[Dict[str, Any]]:
        if self._provider is None:
            return None
        offers = self._provider.open_offers()
        if not offers:
            return None
        goodput = float(window.get("goodput_fraction", -1.0))
        if goodput < 0.0:
            # no measured goodput yet: nothing to predict the marginal
            # contribution from — claiming blind is how rollbacks happen
            return None
        offer = offers[0]
        cost_s = self._actuation_cost_s()
        # predicted productive slice-seconds the offer contributes if
        # the new slice reaches the fleet's measured goodput, amortized
        # over what remains of the offer's lifetime
        gain_s = offer.remaining_s(now) * goodput * offer.slices
        evidence = {
            "offer": offer.to_dict(),
            "goodput_fraction": round(goodput, 4),
            "predicted_gain_s": round(gain_s, 3),
            "actuation_cost_s": round(cost_s, 3),
            "claim_margin": ctx.autoscale_claim_margin,
        }
        if self._plan_calibration is not None:
            try:
                current = self._plan_calibration.current()
                if current:
                    evidence["plan_calibration"] = current
            except Exception:  # noqa: BLE001 — advisory evidence
                pass
        if gain_s <= ctx.autoscale_claim_margin * cost_s:
            return None
        return {"kind": "claim", "evidence": evidence,
                "offer_id": offer.offer_id,
                "reason": (f"offer #{offer.offer_id}: predicted gain "
                           f"{gain_s:.0f}s > {ctx.autoscale_claim_margin:g}"
                           f"× join+re-plan cost {cost_s:.0f}s")}

    def _shed_candidate(self, ctx: Context,
                        window: Dict[str, Any]
                        ) -> Optional[Dict[str, Any]]:
        trace = self._steptrace_summary()
        if not trace or self._rendezvous is None:
            return None
        gating_rank = int(trace.get("dominant_gating_rank", -1))
        dcn_wait = float(trace.get("cross_slice_wait_fraction", -1.0))
        if gating_rank < 0 or dcn_wait < ctx.autoscale_shed_wait_fraction:
            return None
        sid = self._rendezvous.slice_of(gating_rank)
        if sid < 0:
            return None
        slice_map = self._rendezvous.slice_map
        if len(set(slice_map.values())) <= 1:
            # never shed the only slice: the cure would be the disease
            return None
        members = sorted(self._rendezvous.slice_members(sid))
        if not members:
            return None
        evidence = {
            "gating_rank": gating_rank,
            "slice": sid,
            "members": members,
            "cross_slice_wait_fraction": round(dcn_wait, 4),
            "shed_wait_threshold": ctx.autoscale_shed_wait_fraction,
            "dominant_gating_phase": trace.get("dominant_gating_phase",
                                               ""),
            "goodput_fraction": window.get("goodput_fraction", -1.0),
            "degraded_steps_total": self._degraded_steps_total(),
        }
        return {"kind": "shed", "evidence": evidence, "slice": sid,
                "notice_rank": members[0],
                "reason": (f"slice {sid} gates the critical path (rank "
                           f"{gating_rank}); cross-slice wait "
                           f"{dcn_wait:.0%} > "
                           f"{ctx.autoscale_shed_wait_fraction:.0%}")}

    def _degraded_steps_total(self) -> int:
        if self._ledger is None:
            return 0
        try:
            return int(self._ledger.snapshot().get(
                "degraded_steps_total", 0))
        except Exception:  # noqa: BLE001 — advisory evidence
            return 0

    # -- guardrails --------------------------------------------------------
    def _guardrail(self, ctx: Context, now: float,
                   kind: str) -> str:
        """"" = actuate; otherwise the hold reason."""
        until = self._quarantine_until.get(kind, 0.0)
        if now < until:
            return f"quarantined for {until - now:.0f}s more"
        if self._watch is not None:
            return (f"watchdog window open on decision "
                    f"#{self._watch['decision_id']}")
        held = self._hysteresis.get(kind, 0)
        if held < ctx.autoscale_hysteresis_windows:
            return (f"hysteresis {held}/"
                    f"{ctx.autoscale_hysteresis_windows} windows")
        if now - self._last_actuation_ts < ctx.autoscale_cooldown_s:
            return (f"cooldown: {ctx.autoscale_cooldown_s - (now - self._last_actuation_ts):.0f}s"
                    " remaining")
        recent = [ts for ts in self._actuation_window
                  if now - ts < 3600.0]
        if len(recent) >= ctx.autoscale_max_decisions_per_hour:
            return (f"rate limit: {len(recent)} actuations in the "
                    f"last hour (max "
                    f"{ctx.autoscale_max_decisions_per_hour})")
        return ""

    # -- the loop body -----------------------------------------------------
    def evaluate_once(self) -> Optional[Dict[str, Any]]:
        """One evaluation: watchdog first, then candidates, then
        guardrails, then (maybe) actuation. Returns the decision record
        appended to history, None when nothing was worth recording (no
        candidate, no open watch that resolved)."""
        ctx = Context.singleton()
        now = self._now()
        window = self._window(ctx)
        rollback = self._check_watch(ctx, now, window)
        if rollback is not None:
            return rollback
        candidate = self._claim_candidate(ctx, now, window) \
            or self._shed_candidate(ctx, window)
        with self._lock:
            if candidate is None:
                self._hysteresis.clear()
                return None
            kind = candidate["kind"]
            # a flapping candidate class restarts its peer's count:
            # hysteresis measures CONSECUTIVE windows of one condition
            self._hysteresis = {
                kind: self._hysteresis.get(kind, 0) + 1}
            hold_reason = self._guardrail(ctx, now, kind)
        if hold_reason:
            return self._record(
                kind="hold", now=now,
                reason=f"{kind} blocked: {hold_reason}",
                evidence=dict(candidate["evidence"],
                              candidate=kind),
                severity="info")
        return self._actuate(ctx, now, window, candidate)

    def _actuate(self, ctx: Context, now: float,
                 window: Dict[str, Any],
                 candidate: Dict[str, Any]) -> Dict[str, Any]:
        kind = candidate["kind"]
        granted: List[int] = []
        if kind == "claim":
            if self._ledger is not None:
                self._ledger.note_elasticity_event("autoscale")
            result = self._provider.claim(candidate["offer_id"])
            if result is None:
                return self._record(
                    kind="hold", now=now,
                    reason=(f"offer #{candidate['offer_id']} vanished "
                            "before the claim landed"),
                    evidence=candidate["evidence"], severity="info")
            granted = result
        else:  # shed
            if self._ledger is not None:
                self._ledger.note_elasticity_event("autoscale")
            deadline = now + ctx.preempt_default_grace_s
            if self.shed_sink is not None:
                try:
                    self.shed_sink(candidate["notice_rank"], deadline,
                                   f"autoscale: {candidate['reason']}")
                except Exception:  # noqa: BLE001 — the failure is the
                    # watchdog's to price; the decision still records
                    logger.exception("shed actuation failed")
        baseline = float(window.get("goodput_fraction", -1.0))
        record = self._record(
            kind=kind, now=now, reason=candidate["reason"],
            evidence=dict(candidate["evidence"], granted=granted),
            severity="warning" if kind == "shed" else "info")
        with self._lock:
            self._hysteresis.clear()
            self._last_actuation_ts = now
            self._actuation_window.append(now)
            self._watch = {
                "decision_id": record["id"], "kind": kind,
                "baseline": baseline,
                "until": now + ctx.autoscale_rollback_window_s,
                "granted": granted,
            }
        self._sink()
        return record

    # -- rollback watchdog -------------------------------------------------
    def _check_watch(self, ctx: Context, now: float,
                     window: Dict[str, Any]
                     ) -> Optional[Dict[str, Any]]:
        with self._lock:
            watch = self._watch
            if watch is None or now < watch["until"]:
                return None
            self._watch = None
        current = float(window.get("goodput_fraction", -1.0))
        baseline = float(watch.get("baseline", -1.0))
        kind = watch["kind"]
        dropped = (baseline > 0.0 and current >= 0.0
                   and current < baseline
                   * (1.0 - ctx.autoscale_rollback_drop_fraction))
        if not dropped:
            with self._lock:
                self._quarantine_level[kind] = 0
                self._mark_outcome_locked(watch["decision_id"], "ok")
            self._sink()
            return None
        # the actuation made things worse: revert it and quarantine the
        # class, doubling per consecutive rollback
        with self._lock:
            level = self._quarantine_level.get(kind, 0) + 1
            self._quarantine_level[kind] = level
            multiplier = min(_QUARANTINE_MAX_MULTIPLIER,
                             2 ** (level - 1))
            quarantine_s = ctx.autoscale_quarantine_backoff_s \
                * multiplier
            self._quarantine_until[kind] = now + quarantine_s
            self._mark_outcome_locked(watch["decision_id"],
                                      "rolled_back")
            granted = list(watch.get("granted", []))
        reverted: List[int] = []
        if kind == "claim" and granted and self.shed_sink is not None:
            # revert: shed what the bad claim brought in (through the
            # same slice-unit drain chain a shed uses)
            if self._ledger is not None:
                self._ledger.note_elasticity_event("autoscale")
            for sid in granted:
                members = sorted(self._rendezvous.slice_members(sid)) \
                    if self._rendezvous is not None else []
                if not members:
                    continue
                try:
                    self.shed_sink(
                        members[0], now + ctx.preempt_default_grace_s,
                        f"autoscale rollback: reverting claimed slice "
                        f"{sid}")
                    reverted.append(sid)
                except Exception:  # noqa: BLE001 — best-effort revert
                    logger.exception("rollback shed of slice %d failed",
                                     sid)
        obs.get_flight_recorder().record_event(
            "autoscale_rollback", decision_id=watch["decision_id"],
            decision_kind=kind, baseline=round(baseline, 4),
            current=round(current, 4), quarantine_s=quarantine_s,
            reverted=reverted)
        record = self._record(
            kind="rollback", now=now,
            reason=(f"{kind} #{watch['decision_id']} rolled back: "
                    f"windowed goodput {current:.0%} < baseline "
                    f"{baseline:.0%} − "
                    f"{ctx.autoscale_rollback_drop_fraction:.0%}; "
                    f"class quarantined {quarantine_s:.0f}s"),
            evidence={"decision_id": watch["decision_id"],
                      "decision_kind": kind,
                      "baseline": round(baseline, 4),
                      "current": round(current, 4),
                      "quarantine_s": round(quarantine_s, 3),
                      "quarantine_level": level,
                      "reverted": reverted},
            severity="warning")
        self._sink()
        return record

    def _handle_revoke(self, slice_id: int, grace_s: float) -> None:
        """Market revocation listener: a revoked slice under watch
        cancels the watch WITHOUT quarantine — the coming goodput dip
        is the market's doing, not the claim's."""
        with self._lock:
            watch = self._watch
            if watch is not None and slice_id in watch.get("granted",
                                                           []):
                self._watch = None
                self._mark_outcome_locked(watch["decision_id"],
                                          "revoked")
                logger.info(
                    "watch on decision #%d cancelled: claimed slice %d "
                    "revoked by the market", watch["decision_id"],
                    slice_id)
        self._sink()

    # -- bookkeeping -------------------------------------------------------
    def _mark_outcome_locked(self, decision_id: int,
                             outcome: str) -> None:
        for record in self._decisions:
            if record.get("id") == decision_id:
                record["outcome"] = outcome
                return

    def _record(self, kind: str, now: float, reason: str,
                evidence: Dict[str, Any],
                severity: str = "info") -> Dict[str, Any]:
        with self._lock:
            record = {
                "id": self._next_decision_id,
                "kind": kind,
                "ts": now,
                "reason": reason,
                "evidence": evidence,
                "outcome": ("pending" if kind in ("claim", "shed")
                            else ""),
            }
            self._next_decision_id += 1
            self._decisions.append(record)
        self._decisions_total.labels(kind=kind).inc()
        obs.get_flight_recorder().record_event(
            "autoscale_decision", id=record["id"], kind=kind,
            reason=reason[:256], evidence=evidence)
        if self._diagnosis is not None:
            try:
                self._diagnosis.observe_autoscale(kind, reason,
                                                  evidence,
                                                  severity=severity)
            except Exception:  # noqa: BLE001 — reporting, not the loop
                logger.exception("autoscale diagnosis report failed")
        logger.log(30 if severity != "info" else 20,
                   "autoscale [%s]: %s", kind, reason)
        return record

    def _sink(self) -> None:
        sink = self.state_sink
        if sink is None:
            return
        try:
            sink()
        except Exception:  # noqa: BLE001 — durability is best-effort
            logger.exception("fleet-controller state snapshot failed")

    def _quarantined_count(self) -> float:
        now = self._now()
        with self._lock:
            return float(sum(1 for until in
                             self._quarantine_until.values()
                             if until > now))

    # -- tools / RPC view --------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """JSON-safe controller state for the AutoscaleStatusRequest
        RPC and the flight snapshot (tools/diagnose.py render_autoscale
        consumes exactly this shape, live and postmortem)."""
        now = self._now()
        offers = []
        if self._provider is not None:
            try:
                offers = [o.to_dict()
                          for o in self._provider.open_offers()]
            except Exception:  # noqa: BLE001 — view, not the loop
                logger.exception("capacity offers read failed")
        with self._lock:
            return {
                "version": 1,
                "decisions": [dict(d) for d in self._decisions],
                "watch": dict(self._watch) if self._watch else None,
                "quarantine": {
                    kind: {"until": until,
                           "remaining_s": round(max(0.0, until - now),
                                                3),
                           "level": self._quarantine_level.get(kind,
                                                               0)}
                    for kind, until in self._quarantine_until.items()
                    if until > now},
                "last_actuation_ts": self._last_actuation_ts,
                "offers": offers,
            }

    # -- loop --------------------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        interval = (interval_s if interval_s is not None
                    else Context.singleton().autoscale_interval_s)

        def _loop():
            while not self._stopped.wait(interval):
                try:
                    self.evaluate_once()
                except Exception:  # noqa: BLE001 — loop must survive
                    logger.exception("autoscale round failed")

        with self._lock:
            if self._thread is not None:
                return
            self._stopped.clear()
            thread = threading.Thread(target=_loop, daemon=True,
                                      name="fleet-controller")
            self._thread = thread
        thread.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            self._thread = None

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        # stored timestamps are stable values (set once at decision
        # time), so a steady-state export stays byte-identical for
        # save_if_changed dedup
        with self._lock:
            return {
                "decisions": [dict(d) for d in
                              self._decisions][-_PERSISTED_DECISIONS:],
                "next_decision_id": self._next_decision_id,
                "last_actuation_ts": self._last_actuation_ts,
                "actuation_window": list(self._actuation_window),
                "quarantine_until": dict(self._quarantine_until),
                "quarantine_level": dict(self._quarantine_level),
                "watch": dict(self._watch) if self._watch else None,
            }

    def restore_state(self, state: dict) -> None:
        """A promoted standby inherits decision history, cooldowns, the
        rate-limit window, quarantines, and any open watchdog window —
        the guardrails must survive failover or a flapping master could
        double-actuate. Hysteresis restarts empty (its evidence
        re-accumulates within N windows)."""
        with self._lock:
            self._decisions.clear()
            for record in state.get("decisions", []):
                if isinstance(record, dict):
                    self._decisions.append(dict(record))
            self._next_decision_id = max(
                1, int(state.get("next_decision_id", 1)))
            self._last_actuation_ts = float(
                state.get("last_actuation_ts", 0.0))
            self._actuation_window.clear()
            for ts in state.get("actuation_window", []):
                self._actuation_window.append(float(ts))
            self._quarantine_until = {
                str(k): float(v) for k, v in
                (state.get("quarantine_until") or {}).items()}
            self._quarantine_level = {
                str(k): int(v) for k, v in
                (state.get("quarantine_level") or {}).items()}
            watch = state.get("watch")
            self._watch = dict(watch) if isinstance(watch, dict) \
                else None
            self._hysteresis.clear()
