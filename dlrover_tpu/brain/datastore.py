"""Metrics datastore (sqlite).

Capability parity: dlrover/go/brain/pkg/datastore/ (MySQL) — persisted job
metric records keyed by job + record type, queryable for the optimizer
algorithms' historical lookups.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS job_metrics (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_name TEXT NOT NULL,
    job_uuid TEXT DEFAULT '',
    record_type TEXT NOT NULL,
    payload TEXT NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_job_metrics_job
    ON job_metrics (job_name, record_type);
"""


class MetricsStore:
    def __init__(self, path: str = ":memory:"):
        # one connection guarded by a lock: sqlite objects are not
        # thread-safe across the gRPC handler pool
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def persist(self, job_name: str, record_type: str,
                payload: Dict[str, Any], job_uuid: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO job_metrics (job_name, job_uuid, record_type,"
                " payload, created_at) VALUES (?, ?, ?, ?, ?)",
                (job_name, job_uuid, record_type, json.dumps(payload),
                 time.time()),
            )
            self._conn.commit()

    def query(self, job_name: Optional[str] = None,
              record_type: Optional[str] = None,
              limit: int = 1000) -> List[Dict[str, Any]]:
        sql = ("SELECT job_name, job_uuid, record_type, payload, created_at"
               " FROM job_metrics WHERE 1=1")
        args: List[Any] = []
        if job_name:
            sql += " AND job_name = ?"
            args.append(job_name)
        if record_type:
            sql += " AND record_type = ?"
            args.append(record_type)
        sql += " ORDER BY id DESC LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(sql, args).fetchall()
        return [
            {
                "job_name": r[0],
                "job_uuid": r[1],
                "record_type": r[2],
                "payload": json.loads(r[3]),
                "created_at": r[4],
            }
            for r in rows
        ]

    def completed_jobs(self, limit: int = 50) -> List[str]:
        """Names of jobs that reported a successful exit (cold-start
        history source)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT job_name, payload FROM job_metrics"
                " WHERE record_type = 'job_exit' ORDER BY id DESC LIMIT ?",
                (limit * 4,),
            ).fetchall()
        names: List[str] = []
        seen = set()
        for name, payload in rows:
            if name in seen:
                continue
            seen.add(name)
            try:
                if json.loads(payload).get("stage") == "succeeded":
                    names.append(name)
            except json.JSONDecodeError:
                continue
            if len(names) >= limit:
                break
        return names
