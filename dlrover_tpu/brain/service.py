"""Brain service over the 2-RPC comm layer.

Capability parity: dlrover/go/brain/pkg/server/server.go:176 (gRPC Brain
service) — persist_metrics / optimize / get_job_metrics dispatched from the
shared get/report envelope.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

import grpc

from dlrover_tpu.brain.algorithms import run_algorithm
from dlrover_tpu.brain.datastore import MetricsStore
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import build_server
from dlrover_tpu.common.log import default_logger as logger


class BrainService:
    def __init__(self, store: Optional[MetricsStore] = None,
                 port: int = 0, host: str = "0.0.0.0"):
        self.store = store or MetricsStore()
        self._server, self.port = build_server(
            self._get_bytes, self._report_bytes, port=port, host=host)
        self._started = threading.Event()

    def start(self) -> None:
        self._server.start()
        self._started.set()
        logger.info("brain service on port %d", self.port)

    def stop(self, grace_s: float = 0.5) -> None:
        self._server.stop(grace_s)

    # -- wire handlers ---------------------------------------------------
    def _get_bytes(self, payload: bytes,
                   context: grpc.ServicerContext) -> bytes:
        request = msg.deserialize_message(payload)
        return msg.serialize_message(self._get(request))

    def _report_bytes(self, payload: bytes,
                      context: grpc.ServicerContext) -> bytes:
        request = msg.deserialize_message(payload)
        return msg.serialize_message(self._report(request))

    # -- dispatch --------------------------------------------------------
    def _get(self, request) -> msg.Message:
        if isinstance(request, msg.BrainOptimizeRequest):
            config = (json.loads(request.config_json)
                      if request.config_json else {})
            plan = run_algorithm(request.stage, self.store,
                                 request.job_name, config)
            return msg.BrainResourcePlan(plan_json=json.dumps(plan),
                                         found=bool(plan))
        if isinstance(request, msg.BrainJobMetricsRequest):
            records = self.store.query(job_name=request.job_name or None,
                                       record_type=request.record_type
                                       or None)
            return msg.BrainJobMetrics(records_json=json.dumps(records))
        return msg.Response(success=False, reason="unknown request")

    def _report(self, request) -> msg.Message:
        if isinstance(request, msg.BrainMetricsReport):
            try:
                payload = json.loads(request.payload_json or "{}")
            except json.JSONDecodeError:
                return msg.Response(success=False, reason="bad payload")
            self.store.persist(request.job_name, request.record_type,
                               payload, request.job_uuid)
            return msg.Response(success=True)
        return msg.Response(success=False, reason="unknown request")
