"""Brain client + the brain-backed stats reporter and resource optimizer.

Capability parity: BrainClient (dlrover/python/brain/client.py:63) and the
BrainOptimizer variant of JobResourceOptimizer (master/resource/job.py) —
used when optimizeMode == "cluster".
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import MasterStub, build_channel
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.resource.optimizer import (
    ResourceOptimizer,
    ResourcePlan,
)
from dlrover_tpu.master.resource.stats_collector import RuntimeStatsCollector
from dlrover_tpu.master.stats.reporter import StatsReporter


class BrainClient:
    # Finite deadline on every call: the brain is advisory, and a dead
    # brain must never hang the master (especially JobMaster.stop(), which
    # reports job-exit synchronously).
    def __init__(self, addr: str, timeout_s: float = 10.0):
        self._stub = MasterStub(build_channel(addr))
        self._timeout_s = timeout_s

    def persist_metrics(self, job_name: str, record_type: str,
                        payload: Dict[str, Any],
                        job_uuid: str = "") -> bool:
        response = msg.deserialize_message(self._stub.report(
            msg.serialize_message(msg.BrainMetricsReport(
                job_name=job_name, job_uuid=job_uuid,
                record_type=record_type,
                payload_json=json.dumps(payload),
            )), timeout=self._timeout_s))
        return bool(getattr(response, "success", False))

    def optimize(self, job_name: str, stage: str,
                 config: Optional[Dict] = None) -> Dict[str, Any]:
        response = msg.deserialize_message(self._stub.get(
            msg.serialize_message(msg.BrainOptimizeRequest(
                job_name=job_name, stage=stage,
                config_json=json.dumps(config or {}),
            )), timeout=self._timeout_s))
        if isinstance(response, msg.BrainResourcePlan) and response.found:
            # advisory resource plan, not a world-stamped execution
            # plan: the brain has no epoch/generation to validate, and
            # the auto-scaler re-checks cluster state before acting
            return json.loads(response.plan_json)  # graftlint: disable=GL704
        return {}

    def get_job_metrics(self, job_name: str,
                        record_type: str = "") -> list:
        response = msg.deserialize_message(self._stub.get(
            msg.serialize_message(msg.BrainJobMetricsRequest(
                job_name=job_name, record_type=record_type,
            )), timeout=self._timeout_s))
        if isinstance(response, msg.BrainJobMetrics):
            return json.loads(response.records_json)
        return []


class BrainReporter(StatsReporter):
    """StatsReporter that persists into the brain service."""

    def __init__(self, addr: str, job_name: str, job_uuid: str = ""):
        self._client = BrainClient(addr)
        self._job_name = job_name
        self._job_uuid = job_uuid

    def report(self, record_type: str, payload: Dict[str, Any]) -> None:
        try:
            self._client.persist_metrics(self._job_name, record_type,
                                         payload, self._job_uuid)
        except Exception as e:  # noqa: BLE001 - reporting is best-effort
            logger.warning("brain report failed: %s", e)


def _plan_from_json(raw: Dict[str, Any]) -> ResourcePlan:
    plan = ResourcePlan()
    for node_type, fields in (raw.get("node_group_resources") or {}).items():
        plan.node_group_resources[node_type] = NodeGroupResource(
            count=int(fields.get("count", 0)),
            node_resource=NodeResource(
                cpu=float(fields.get("cpu", 0)),
                memory_mb=float(fields.get("memory_mb", 0)),
                chips=int(fields.get("chips", 0)),
                chip_type=fields.get("chip_type", ""),
            ),
        )
    plan.dataloader_workers = int(raw.get("dataloader_workers", 0))
    return plan


class BrainResourceOptimizer(ResourceOptimizer):
    """ResourceOptimizer backed by the brain service, falling back to the
    local optimizer when the brain has no answer (reference:
    JobResourceOptimizer's brain-with-local-fallback, master/resource/job.py)."""

    def __init__(self, addr: str, job_name: str,
                 stats: Optional[RuntimeStatsCollector] = None):
        from dlrover_tpu.master.resource.local_optimizer import (
            LocalResourceOptimizer,
        )

        self._client = BrainClient(addr)
        self._job_name = job_name
        self._local = LocalResourceOptimizer(stats=stats)
        self.stats = self._local.stats

    def generate_plan(self, stage: str,
                      config: Optional[dict] = None) -> ResourcePlan:
        try:
            raw = self._client.optimize(self._job_name, stage, config)
        except Exception as e:  # noqa: BLE001 - brain outage ≠ job outage
            logger.warning("brain optimize failed: %s; using local", e)
            raw = {}
        if raw:
            return _plan_from_json(raw)
        return self._local.generate_plan(stage, config)
