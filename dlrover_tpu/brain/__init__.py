"""Brain: cluster-wide resource optimization from historical job metrics.

Capability parity: dlrover/go/brain/ (gRPC Brain service — persist_metrics,
optimize, get_job_metrics; dlrover/proto/brain.proto:196-200; MySQL
datastore; pluggable optimizer algorithms in
pkg/optimizer/implementation/optalgorithm/). TPU-native re-design: the
same 3 operations over this framework's 2-RPC comm layer, a sqlite
datastore (stdlib, zero-dep), and algorithms re-framed for TPU jobs (host
shapes + chip counts instead of PS CPU). Only consulted when
optimizeMode == "cluster"; single-job mode never needs it.
"""

from dlrover_tpu.brain.client import BrainClient, BrainReporter
from dlrover_tpu.brain.service import BrainService

__all__ = ["BrainClient", "BrainReporter", "BrainService"]
