"""WSAM: weighted sharpness-aware minimization.

Capability parity: atorch/optimizers/wsam.py (KDD'23 "Sharpness-Aware
Minimization Revisited: Weighted Sharpness as a Regularization Term",
atorch/atorch/optimizers/README.md:1-10). Minimizes
L(w) + γ/(1-γ) · [max_{||ε||≤ρ} L(w+ε) - L(w)], i.e. the WSAM gradient is

    g_wsam = g + γ/(1-γ) · (g_adv − g)       (γ=0.5 ⇒ vanilla SAM)

TPU re-design: no in-place parameter perturbation / two optimizer.step
calls — a pure `value_and_grad`-shaped function computes both gradients
inside one jitted program (XLA overlaps the two backward passes where
possible) and composes with any optax transformation.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax


def wsam_value_and_grad(
    loss_fn: Callable[..., jax.Array],
    rho: float = 0.05,
    gamma: float = 0.5,
) -> Callable[..., Tuple[jax.Array, Any]]:
    """Wrap `loss_fn(params, *args)` into WSAM (value, grad).

    Use exactly like `jax.value_and_grad(loss_fn)`:
        value_and_grad = wsam_value_and_grad(loss_fn, rho=0.05)
        loss, grads = value_and_grad(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
    """
    if not 0.0 <= gamma < 1.0:
        raise ValueError(f"gamma must be in [0, 1), got {gamma}")
    sharpness_weight = gamma / (1.0 - gamma)

    def value_and_grad(params, *args, **kwargs):
        loss, grads = jax.value_and_grad(loss_fn)(params, *args, **kwargs)
        grad_norm = optax.global_norm(grads)
        scale = rho / jnp.maximum(grad_norm, 1e-12)
        adv_params = jax.tree.map(lambda p, g: p + scale * g, params,
                                  grads)
        adv_grads = jax.grad(loss_fn)(adv_params, *args, **kwargs)
        wsam_grads = jax.tree.map(
            lambda g, ga: g + sharpness_weight * (ga - g), grads,
            adv_grads)
        return loss, wsam_grads

    return value_and_grad
