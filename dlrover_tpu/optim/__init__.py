"""Optimizer family (optax gradient transformations).

Capability parity: atorch/optim + atorch/optimizers —
- `agd`            ≙ atorch/optim/agd.py (AGD, NeurIPS'23: gradient-
                     difference preconditioner with auto SGD/adaptive switch)
- `wsam_*`         ≙ atorch/optimizers/wsam.py (WSAM, KDD'23 weighted
                     sharpness-aware minimization)
- `bf16_master`    ≙ atorch/optim/bf16_optimizer.py (bf16 params with
                     fp32 master copies)
- `row_sparse_adagrad` ≙ atorch/optim/sparse adagrad/adam (embedding-row
                     sparse updates)
"""

from dlrover_tpu.optim.agd import agd
from dlrover_tpu.optim.bf16 import bf16_master
from dlrover_tpu.optim.sparse import (
    row_sparse_adagrad,
    row_sparse_adam,
    row_sparse_sgd,
)
from dlrover_tpu.optim.wsam import wsam_value_and_grad

__all__ = ["agd", "bf16_master", "row_sparse_adagrad",
           "row_sparse_adam", "row_sparse_sgd",
           "wsam_value_and_grad"]
