"""Row-sparse adagrad for embedding tables.

Capability parity: atorch/optim/ sparse adagrad/adam — only embedding rows
touched in the step get accumulator/parameter updates. TPU re-design: XLA
has no sparse tensors; "sparse" means masking by row activity (rows with
zero gradient stay bit-identical, including their accumulators), which is
exactly the semantics sparse optimizers give embeddings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class RowSparseAdagradState(NamedTuple):
    accumulator: optax.Updates


def row_sparse_adagrad(
    learning_rate: float = 0.1,
    initial_accumulator: float = 0.1,
    eps: float = 1e-10,
) -> optax.GradientTransformation:
    def init_fn(params):
        return RowSparseAdagradState(
            accumulator=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator), params))

    def update_fn(updates, state, params=None):
        del params

        def one(g, acc):
            if g.ndim < 2:
                row_active = jnp.any(g != 0)
            else:
                row_active = jnp.any(
                    g.reshape(g.shape[0], -1) != 0, axis=-1)
                row_active = row_active.reshape(
                    (g.shape[0],) + (1,) * (g.ndim - 1))
            new_acc = jnp.where(row_active, acc + jnp.square(g), acc)
            step = jnp.where(
                row_active,
                -learning_rate * g / (jnp.sqrt(new_acc) + eps),
                jnp.zeros_like(g))
            return step, new_acc

        flat = jax.tree.map(one, updates, state.accumulator,
                            is_leaf=lambda x: isinstance(x, jnp.ndarray))
        steps = jax.tree.map(lambda pair: pair[0], flat,
                             is_leaf=lambda x: isinstance(x, tuple))
        accs = jax.tree.map(lambda pair: pair[1], flat,
                            is_leaf=lambda x: isinstance(x, tuple))
        return steps, RowSparseAdagradState(accumulator=accs)

    return optax.GradientTransformation(init_fn, update_fn)


def _row_active_mask(g):
    """(rows, 1, ...) bool mask of rows with any nonzero gradient."""
    if g.ndim < 2:
        return jnp.any(g != 0)
    active = jnp.any(g.reshape(g.shape[0], -1) != 0, axis=-1)
    return active.reshape((g.shape[0],) + (1,) * (g.ndim - 1))


class RowSparseAdamState(NamedTuple):
    mu: optax.Updates
    nu: optax.Updates
    counts: optax.Updates      # per-row step counts (bias correction)


def row_sparse_adam(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Adam where only rows touched in the step update — moments AND the
    per-row bias-correction counts of untouched rows stay bit-identical
    (capability parity: atorch sparse adam; see module docstring)."""

    def init_fn(params):
        zeros = lambda p: jnp.zeros_like(p)
        counts = jax.tree.map(
            lambda p: jnp.zeros((p.shape[0],) + (1,) * (p.ndim - 1)
                                if p.ndim >= 2 else (), jnp.int32),
            params)
        return RowSparseAdamState(
            mu=jax.tree.map(zeros, params),
            nu=jax.tree.map(zeros, params),
            counts=counts)

    def update_fn(updates, state, params=None):
        del params

        def one(g, mu, nu, count):
            active = _row_active_mask(g)
            new_count = jnp.where(active, count + 1, count)
            new_mu = jnp.where(active, b1 * mu + (1 - b1) * g, mu)
            new_nu = jnp.where(active, b2 * nu + (1 - b2) * jnp.square(g),
                               nu)
            t = jnp.maximum(new_count, 1).astype(jnp.float32)
            mu_hat = new_mu / (1 - b1 ** t)
            nu_hat = new_nu / (1 - b2 ** t)
            step = jnp.where(
                active,
                -learning_rate * mu_hat / (jnp.sqrt(nu_hat) + eps),
                jnp.zeros_like(g))
            return step, new_mu, new_nu, new_count

        is_arr = lambda x: isinstance(x, jnp.ndarray)
        quads = jax.tree.map(one, updates, state.mu, state.nu,
                             state.counts, is_leaf=is_arr)
        pick = lambda i: jax.tree.map(
            lambda q: q[i], quads, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), RowSparseAdamState(mu=pick(1), nu=pick(2),
                                           counts=pick(3))

    return optax.GradientTransformation(init_fn, update_fn)


class RowSparseSgdState(NamedTuple):
    momentum: optax.Updates


def row_sparse_sgd(
    learning_rate: float = 0.01,
    momentum: float = 0.9,
) -> optax.GradientTransformation:
    """SGD-with-momentum where untouched rows' buffers stay bit-identical
    (capability parity: atorch sparse sgd)."""

    def init_fn(params):
        return RowSparseSgdState(
            momentum=jax.tree.map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        del params

        def one(g, buf):
            active = _row_active_mask(g)
            new_buf = jnp.where(active, momentum * buf + g, buf)
            step = jnp.where(active, -learning_rate * new_buf,
                             jnp.zeros_like(g))
            return step, new_buf

        is_arr = lambda x: isinstance(x, jnp.ndarray)
        pairs = jax.tree.map(one, updates, state.momentum, is_leaf=is_arr)
        is_tup = lambda x: isinstance(x, tuple)
        return (jax.tree.map(lambda p: p[0], pairs, is_leaf=is_tup),
                RowSparseSgdState(momentum=jax.tree.map(
                    lambda p: p[1], pairs, is_leaf=is_tup)))

    return optax.GradientTransformation(init_fn, update_fn)
