"""Row-sparse adagrad for embedding tables.

Capability parity: atorch/optim/ sparse adagrad/adam — only embedding rows
touched in the step get accumulator/parameter updates. TPU re-design: XLA
has no sparse tensors; "sparse" means masking by row activity (rows with
zero gradient stay bit-identical, including their accumulators), which is
exactly the semantics sparse optimizers give embeddings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class RowSparseAdagradState(NamedTuple):
    accumulator: optax.Updates


def row_sparse_adagrad(
    learning_rate: float = 0.1,
    initial_accumulator: float = 0.1,
    eps: float = 1e-10,
) -> optax.GradientTransformation:
    def init_fn(params):
        return RowSparseAdagradState(
            accumulator=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator), params))

    def update_fn(updates, state, params=None):
        del params

        def one(g, acc):
            if g.ndim < 2:
                row_active = jnp.any(g != 0)
            else:
                row_active = jnp.any(
                    g.reshape(g.shape[0], -1) != 0, axis=-1)
                row_active = row_active.reshape(
                    (g.shape[0],) + (1,) * (g.ndim - 1))
            new_acc = jnp.where(row_active, acc + jnp.square(g), acc)
            step = jnp.where(
                row_active,
                -learning_rate * g / (jnp.sqrt(new_acc) + eps),
                jnp.zeros_like(g))
            return step, new_acc

        flat = jax.tree.map(one, updates, state.accumulator,
                            is_leaf=lambda x: isinstance(x, jnp.ndarray))
        steps = jax.tree.map(lambda pair: pair[0], flat,
                             is_leaf=lambda x: isinstance(x, tuple))
        accs = jax.tree.map(lambda pair: pair[1], flat,
                            is_leaf=lambda x: isinstance(x, tuple))
        return steps, RowSparseAdagradState(accumulator=accs)

    return optax.GradientTransformation(init_fn, update_fn)
