"""bf16 training with fp32 master parameters.

Capability parity: atorch/optim/bf16_optimizer.py (265 LoC: fp32 master
weights + bf16 model weights, update in fp32, copy back). As an optax
wrapper: the state carries the fp32 master copy; the inner transformation
runs entirely in fp32; the emitted update is the bf16 delta, so the
visible params stay bf16 while accumulation error does not compound.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class BF16MasterState(NamedTuple):
    master: optax.Params     # fp32 copy
    inner: optax.OptState


def bf16_master(
    inner: optax.GradientTransformation,
) -> optax.GradientTransformation:
    def init_fn(params):
        master = jax.tree.map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return BF16MasterState(master=master, inner=inner.init(master))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("bf16_master requires params")
        grads32 = jax.tree.map(
            lambda g: g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g, updates)
        inner_updates, inner_state = inner.update(
            grads32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, inner_updates)
        # emitted update reproduces the bf16 image of the fp32 master
        new_updates = jax.tree.map(
            lambda nm, p: nm.astype(p.dtype) - p
            if jnp.issubdtype(p.dtype, jnp.floating) else jnp.zeros_like(p),
            new_master, params)
        return new_updates, BF16MasterState(master=new_master,
                                            inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)
