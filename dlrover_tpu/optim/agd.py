"""AGD: auto-switchable optimizer with gradient-difference preconditioning.

Capability parity: atorch/optim/agd.py (AGD, AntGroup NeurIPS'23 "AGD: an
Auto-switchable Optimizer using Stepwise Gradient Difference for
Preconditioning Matrix"). The diagonal preconditioner accumulates the
squared STEPWISE GRADIENT DIFFERENCE instead of the squared gradient; the
`delta` threshold auto-switches each coordinate between adaptive (divide
by sqrt(b)) and SGD-like (divide by delta) behavior.
"""

from __future__ import annotations

from typing import NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax


class AGDState(NamedTuple):
    count: chex.Array
    mu: optax.Updates       # first moment
    nu: optax.Updates       # moment-difference second moment


def agd(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    def init_fn(params):
        return AGDState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        count = state.count + 1
        mu = optax.incremental_update(updates, state.mu, 1 - b1)
        # The preconditioner accumulates the squared difference of
        # BIAS-CORRECTED first moments, mu_hat_t - mu_hat_{t-1} (the paper's
        # stepwise gradient difference is on the smoothed gradient). At
        # count==1 the previous moment is zero, so the diff degenerates to
        # the raw gradient — the Adam-like bootstrap falls out naturally.
        prev_bc = jnp.where(count == 1, 1.0, 1.0 - b1 ** (count - 1))
        cur_bc = 1.0 - b1 ** count
        diff = jax.tree.map(
            lambda m, pm: m / cur_bc - pm / prev_bc, mu, state.mu)
        nu = jax.tree.map(
            lambda n, d: b2 * n + (1 - b2) * jnp.square(d),
            state.nu, diff)
        mu_hat = optax.bias_correction(mu, b1, count)
        nu_hat = optax.bias_correction(nu, b2, count)
        # auto switch: max(sqrt(nu_hat), delta) — coordinates with small
        # curvature proxy fall back to SGD scaling 1/delta
        new_updates = jax.tree.map(
            lambda m, v: m / jnp.maximum(jnp.sqrt(v) + eps, delta),
            mu_hat, nu_hat)
        if weight_decay:
            if params is None:
                raise ValueError("weight_decay requires params")
            new_updates = jax.tree.map(
                lambda u, p: u + weight_decay * p, new_updates, params)
        return new_updates, AGDState(count=count, mu=mu, nu=nu)

    tx = optax.GradientTransformation(init_fn, update_fn)
    return optax.chain(
        tx, optax.scale_by_learning_rate(learning_rate))
