"""Flight recorder: a bounded ring of recent spans/events per process.

A failover postmortem needs the *timeline* — worker died → rendezvous →
recompile → resume — not log archaeology across five processes. Every
process keeps the last N telemetry records in memory and dumps them to
JSON:

- on demand (`dump()`, `tools/obs_dump.py` pretty-prints the file),
- on SIGTERM (the agent sends it before a membership-change restart),
- on an unhandled exception (excepthook chain).

Dumps land in ``$DLROVER_TPU_FLIGHT_DIR`` (default: the system temp
dir's ``dlrover-tpu-flight/``), named ``flight-<role>-<pid>.json``.

Records are plain dicts ({"kind": "span"|"event", "ts": ..., ...});
span records come from `obs.spans` via the default sink, event records
from `record_event` (worker spawn/exit, scale decisions, signals).

stdlib-only by design.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# env override for where dumps land (default: <tempdir>/dlrover-tpu-flight)
FLIGHT_DIR_ENV = "DLROVER_TPU_FLIGHT_DIR"
_DEFAULT_CAPACITY = 4096


def _context_capacities() -> tuple:
    """(event_ring, span_dedup_ring) from the Context knobs
    ``flight_ring_events`` / ``flight_ring_spans`` (env-overridable like
    every knob). obs/ stays importable without the config layer — any
    failure falls back to the historical 4096."""
    try:
        from dlrover_tpu.common.config import Context

        ctx = Context.singleton()
        return (max(1, int(ctx.flight_ring_events)),
                max(1, int(ctx.flight_ring_spans)))
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return _DEFAULT_CAPACITY, _DEFAULT_CAPACITY


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None, role: str = "",
                 dump_dir: str = "", span_capacity: Optional[int] = None):
        # REENTRANT: the SIGTERM handler records + dumps on the very
        # thread it interrupted, which may already hold this lock (every
        # span dispatch appends here) — a plain Lock would deadlock the
        # process in exactly the platform-termination window
        self._lock = threading.RLock()
        ctx_events, ctx_spans = _context_capacities()
        if capacity is None:
            capacity = ctx_events
        if span_capacity is None:
            # an explicit event capacity (tests sizing tiny rings) keeps
            # the historical behavior of sizing both rings together
            span_capacity = capacity if capacity != ctx_events else ctx_spans
        self._events: deque = deque(maxlen=max(1, capacity))
        # span ids already recorded: a standalone master+agent process
        # sees its own spans twice (local sink + telemetry relay)
        self._seen_span_ids: deque = deque(maxlen=max(1, span_capacity))
        self._seen_set: set = set()
        self._role = role or os.environ.get(
            "DLROVER_TPU_NODE_TYPE", "process")
        self._dump_dir = dump_dir
        self._prev_handlers: Dict[int, Any] = {}
        self._prev_excepthook = None
        self._last_dump_path = ""

    # -- recording ---------------------------------------------------------
    def record_event(self, name: str, **attrs: Any) -> None:
        self._append({"kind": "event", "name": name, "ts": time.time(),
                      "pid": os.getpid(), "attrs": attrs})

    def record_span(self, span) -> bool:
        """Accepts an `obs.spans.Span` or an already-serialized dict
        (spans arriving from another process). Re-deliveries of the same
        span id (local sink + telemetry relay in a standalone process)
        are dropped; returns whether the span was newly recorded."""
        record = span if isinstance(span, dict) else span.to_dict()
        span_id = record.get("span_id")
        with self._lock:
            if span_id:
                if span_id in self._seen_set:
                    return False
                if len(self._seen_span_ids) == self._seen_span_ids.maxlen:
                    self._seen_set.discard(self._seen_span_ids[0])
                self._seen_span_ids.append(span_id)
                self._seen_set.add(span_id)
            self._events.append(record)
            return True

    def _append(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(record)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # -- dumping -----------------------------------------------------------
    def _resolve_dir(self) -> str:
        import tempfile

        return (self._dump_dir or os.environ.get(FLIGHT_DIR_ENV, "")
                or os.path.join(tempfile.gettempdir(),
                                "dlrover-tpu-flight"))

    def dump(self, path: str = "", reason: str = "on-demand") -> str:
        """Write the ring to JSON; returns the path. Never raises (a
        crash-path dump failing must not mask the crash)."""
        try:
            if not path:
                directory = self._resolve_dir()
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(
                    directory,
                    f"flight-{self._role}-{os.getpid()}.json")
            payload = {
                "version": 1,
                "role": self._role,
                "pid": os.getpid(),
                "host": socket.gethostname(),
                "reason": reason,
                "dumped_at": time.time(),
                "events": sorted(self.snapshot(),
                                 key=lambda e: e.get("ts", 0.0)),
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, default=str)
            os.replace(tmp, path)
            with self._lock:
                self._last_dump_path = path
            return path
        except Exception:  # noqa: BLE001 — crash-path safety
            return ""

    @property
    def last_dump_path(self) -> str:
        with self._lock:
            return self._last_dump_path

    # -- crash / signal hooks ---------------------------------------------
    def install_signal_handlers(self, signals=(signal.SIGTERM,)) -> None:
        """Dump on the given signals, then chain the previous handler
        (the elastic loop's SIGTERM save handler keeps working). Only
        callable from the main thread (CPython signal contract)."""

        def _make(signum_captured):
            def _handler(signum, frame):
                self.record_event("signal", signum=signum_captured)
                self.dump(reason=f"signal-{signum_captured}")
                prev = self._prev_handlers.get(signum_captured)
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    # re-raise with the default disposition so the
                    # process still dies the way the sender expects
                    signal.signal(signum_captured, signal.SIG_DFL)
                    os.kill(os.getpid(), signum_captured)
            return _handler

        for signum in signals:
            prev = signal.signal(signum, _make(signum))
            self._prev_handlers[signum] = prev

    def uninstall_signal_handlers(self) -> None:
        for signum, prev in self._prev_handlers.items():
            signal.signal(signum, prev)
        self._prev_handlers.clear()

    def install_excepthook(self) -> None:
        """Dump on an unhandled exception, then chain."""
        if self._prev_excepthook is not None:
            return
        prev = sys.excepthook

        def _hook(exc_type, exc, tb):
            self.record_event("unhandled_exception",
                              exc_type=exc_type.__name__,
                              message=str(exc)[:512])
            self.dump(reason="crash")
            prev(exc_type, exc, tb)

        self._prev_excepthook = prev
        sys.excepthook = _hook


_default_lock = threading.Lock()
_default_recorder: Optional[FlightRecorder] = None


def get_flight_recorder() -> FlightRecorder:
    """Per-process default recorder (created lazily)."""
    global _default_recorder
    with _default_lock:
        if _default_recorder is None:
            _default_recorder = FlightRecorder()
        return _default_recorder
