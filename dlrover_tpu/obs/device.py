"""Device-truth worker telemetry: per-step HBM peak watermark + compile
events.

The agent's 15 s monitor tick samples ``bytes_in_use`` BETWEEN steps —
the inter-step trough — so the number that actually OOMs on the next
batch bump (the transient in-step peak) was invisible. jax exposes the
truth: ``device.memory_stats()['peak_bytes_in_use']`` is the
allocator's high-water mark, and reading it once per step costs one C
call per local device. :class:`DeviceTelemetry` tracks that watermark,
notes the step it last ROSE at (the attribution a postmortem wants:
"the peak moved when the batch grew at step 1200"), and hands the
report-window peak to the step report (``GlobalStepReport.
hbm_peak_bytes``) — riding the existing channel, no new RPC.

CPU-safe no-op by contract: a backend whose ``memory_stats()`` answers
nothing disables sampling after one probe — no forever-0 series, no
per-step cost.

Compile events: :func:`record_compile_event` stamps one flight event +
gauges per AOT compile with the wall time and the compiled step's
``cost_analysis`` FLOPs/bytes — the measured program cost the MFU
cross-check and the planner calibration read, not the analytic guess.

stdlib-only at import time (jax is imported lazily inside the sampler),
so the master, tools and jax-free test workers import this bare.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

# a watermark move smaller than this is allocator noise, not a rise
_RISE_THRESHOLD_BYTES = 1 << 20


def _jax_sampler() -> Optional[List[Dict[str, float]]]:
    """Per-local-device memory stats; None when the backend answers
    nothing (CPU) — the availability probe's signal."""
    import jax

    out = []
    for device in jax.local_devices():
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — backend support varies
            stats = None
        if not stats:
            continue
        out.append({
            "index": float(device.id),
            "bytes_in_use": float(stats.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": float(
                stats.get("peak_bytes_in_use",
                          stats.get("bytes_in_use", 0)) or 0),
            "bytes_limit": float(stats.get("bytes_limit", 0) or 0),
        })
    return out or None


class DeviceTelemetry:
    """Per-step HBM watermark tracker for the training loop.

    ``on_step`` is the hot-path call (one ``memory_stats`` per local
    device, nothing else); ``drain`` is the report-interval call that
    returns the window's peak + where it last rose and re-arms the
    window. All cheap enough that the overhead-bound test pins sampler
    cost under 1 % of a CPU bench step.
    """

    def __init__(self, sampler: Optional[
            Callable[[], Optional[List[Dict[str, float]]]]] = None):
        self._sampler = sampler if sampler is not None else _jax_sampler
        self._lock = threading.Lock()
        # None = not probed yet; False = backend has no memory stats
        # (CPU) — every later on_step returns immediately
        self._available: Optional[bool] = None
        self._watermark_bytes = 0.0      # lifetime high-water observed
        # peak_bytes_in_use is a MONOTONE allocator counter (never
        # resets within a process), so "the window's peak" cannot be
        # read off it directly — a drained window would just re-report
        # the lifetime high forever and a resolved pressure episode
        # could never clear. But for a FIXED compiled program the
        # in-step peak recurs every step by construction — a flat
        # counter does not mean the pressure resolved, it means the
        # same program is still peaking at the same level. So the
        # episode boundary is the RECOMPILE (note_recompile — a replan
        # or batch change builds a new program): the window carries the
        # lifetime watermark while the program that set it is still the
        # one running steps (or when it rose in-window); only after a
        # recompile that does NOT re-reach it does the window fall back
        # to its max bytes_in_use as the best live evidence.
        self._window_rose = False        # watermark advanced this window
        self._window_sampled = False     # any step sampled this window
        self._window_in_use_bytes = 0.0  # max bytes_in_use this window
        self._program_epoch = 0          # bumped per note_recompile
        self._watermark_epoch = 0        # program that set the watermark
        self._trough_bytes = 0.0         # last between-step bytes_in_use
        self._limit_bytes = 0.0
        self._rise_step = -1             # step the watermark last rose

    @property
    def available(self) -> Optional[bool]:
        with self._lock:
            return self._available

    def on_step(self, step: int) -> None:
        """Sample after a finished step; no-op once probed unavailable."""
        with self._lock:
            if self._available is False:
                return
        try:
            stats = self._sampler()
        except Exception:  # noqa: BLE001 — telemetry never kills a step
            stats = None
        with self._lock:
            if not stats:
                if self._available is None:
                    self._available = False
                return
            self._available = True
            peak = max(s["peak_bytes_in_use"] for s in stats)
            in_use = max(s["bytes_in_use"] for s in stats)
            self._trough_bytes = in_use
            self._limit_bytes = max(self._limit_bytes,
                                    max(s["bytes_limit"] for s in stats))
            if peak > self._watermark_bytes + _RISE_THRESHOLD_BYTES:
                self._rise_step = int(step)
                self._window_rose = True
                self._watermark_epoch = self._program_epoch
            self._watermark_bytes = max(self._watermark_bytes, peak)
            self._window_sampled = True
            self._window_in_use_bytes = max(self._window_in_use_bytes,
                                            in_use)

    def note_recompile(self) -> None:
        """The train step was (re)compiled: a new program is about to
        run, so the old program's recurring peak stops being evidence
        unless the new one re-reaches it."""
        with self._lock:
            self._program_epoch += 1

    def drain(self) -> Dict[str, float]:
        """Report-window summary for the step report; re-arms the
        window. ``hbm_peak_bytes`` 0 = no device truth (CPU).

        The window peak is the lifetime watermark while the program
        that set it still ran steps this window (steady-state pressure
        recurs every step — HbmPressureRule must not read a flat
        monotone counter as resolved), else the window's max
        ``bytes_in_use`` — so an episode resolved by a recompile
        (smaller batch after a replan) stops re-reporting the old high
        and the rule can actually clear."""
        with self._lock:
            episode_live = (self._window_sampled
                            and self._watermark_epoch
                            == self._program_epoch)
            peak = (self._watermark_bytes
                    if self._window_rose or episode_live
                    else self._window_in_use_bytes)
            out = {
                "hbm_peak_bytes": peak,
                "hbm_watermark_bytes": self._watermark_bytes,
                "hbm_trough_bytes": self._trough_bytes,
                "hbm_limit_bytes": self._limit_bytes,
                "hbm_rise_step": float(self._rise_step),
            }
            self._window_rose = False
            self._window_sampled = False
            self._window_in_use_bytes = 0.0
        return out

    def peak_mb(self) -> float:
        """Lifetime watermark in MiB (0 = unavailable)."""
        with self._lock:
            return self._watermark_bytes / (1 << 20)


def cost_summary(compiled) -> Dict[str, float]:
    """FLOPs + bytes-accessed of an XLA-compiled program from its
    ``cost_analysis()`` — zeros whenever the backend cannot answer
    (advisory by contract, like obs.mfu.cost_analysis_flops)."""
    out = {"flops": 0.0, "bytes_accessed": 0.0}
    if compiled is None:
        return out
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend support varies
        return out
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return out
    for key, field in (("flops", "flops"),
                       ("bytes accessed", "bytes_accessed")):
        try:
            out[field] = float(analysis.get(key, 0.0) or 0.0)
        except (TypeError, ValueError):
            pass
    return out


def record_compile_event(wall_s: float, compiled=None,
                         kind: str = "aot",
                         mesh: Optional[Dict[str, Any]] = None) -> Dict[
                             str, float]:
    """One compile's device truth into the flight recorder + gauges:
    wall time plus the compiled step's cost-analysis FLOPs/bytes. The
    event is what ``tools/top.py --flight`` and the calibration table
    read; returns the cost summary so callers reuse it."""
    from dlrover_tpu.obs.flight_recorder import get_flight_recorder
    from dlrover_tpu.obs.metrics import get_registry

    costs = cost_summary(compiled)
    get_flight_recorder().record_event(
        "compile_event", kind=kind, wall_s=round(float(wall_s), 3),
        flops=costs["flops"], bytes_accessed=costs["bytes_accessed"],
        mesh=dict(mesh) if mesh else None)
    registry = get_registry()
    registry.gauge(
        "dlrover_tpu_compile_wall_seconds",
        "Wall-clock of the last train-step compile",
        labelnames=("kind",)).labels(kind=kind).set(float(wall_s))
    if costs["flops"] > 0:
        registry.gauge(
            "dlrover_tpu_compiled_step_flops",
            "XLA cost-analysis FLOPs of the last compiled train step"
        ).set(costs["flops"])
    if costs["bytes_accessed"] > 0:
        registry.gauge(
            "dlrover_tpu_compiled_step_bytes_accessed",
            "XLA cost-analysis bytes accessed of the last compiled "
            "train step").set(costs["bytes_accessed"])
    return costs
