"""On-demand jax.profiler trace capture.

Two triggers, one mechanism:

- **Static window** (config/env): trace steps [start, start+num) of every
  (re)spawn — the TorchTitan-style built-in profiling window.
- **On-demand**: the agent (executing a master ``profile:{rank}``
  diagnosis action) atomically writes a request file
  (``$DLROVER_TPU_PROFILE_REQUEST``, JSON ``{"id", "num_steps",
  "dump_dir"}``); the worker's step loop polls it (one ``os.stat`` per
  step — cheap) and runs a bounded capture.

Every capture gets its own directory (``capture-<id>-<ts>``) under the
dump dir, holding whatever the jax profiler wrote plus a
``manifest.json`` recording the step window and outcome; the manifest is
also mirrored to ``<request>.done`` so the agent can observe completion
without knowing the capture layout. All failure modes degrade to a
manifest with ``status != "ok"`` — profiling is diagnostics, it must
never kill (or even slow) training when the backend can't trace
(``no-op safe on CPU``: jax's CPU profiler usually works, but e.g. a
second concurrent session raising must not propagate into the step
loop).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from dlrover_tpu.common.log import default_logger as logger


def _write_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def write_profile_request(path: str, request_id: int, num_steps: int,
                          dump_dir: str) -> None:
    """Agent side: atomically publish a capture request for the worker's
    poll loop. A new ``id`` supersedes any previous request."""
    _write_json(path, {"id": int(request_id),
                       "num_steps": int(num_steps),
                       "dump_dir": dump_dir})


def read_profile_result(path: str) -> Optional[Dict[str, Any]]:
    """Agent side: the worker's completion manifest for the request at
    ``path`` (None until the capture finishes)."""
    try:
        with open(path + ".done") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


class ProfilerCapture:
    """One bounded trace capture. Not thread-safe by itself — driven only
    from the step loop via ProfilerSession."""

    def __init__(self, dump_dir: str, num_steps: int,
                 request_id: int = 0, start_step: int = 0):
        self.request_id = request_id
        self.num_steps = max(1, int(num_steps))
        self.start_step = start_step
        self.status = "pending"
        ts = int(time.time())
        self.trace_dir = os.path.join(
            dump_dir, f"capture-{request_id}-{ts}")

    def start(self) -> bool:
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
        except OSError as e:
            self.status = f"error: mkdir failed: {e}"
            return False
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:  # noqa: BLE001 — backend-dependent
            # the capture artifact still lands (manifest records why the
            # trace itself is absent) so the action round-trip is
            # observable even where the profiler is unavailable
            self.status = f"unavailable: {e}"
            logger.warning("jax profiler unavailable: %s", e)
            return False
        self.status = "tracing"
        return True

    def stop(self) -> Dict[str, Any]:
        """End the trace (if one started) and write the manifest; returns
        the manifest dict. Never raises."""
        if self.status == "tracing":
            try:
                import jax

                jax.profiler.stop_trace()
                self.status = "ok"
            except Exception as e:  # noqa: BLE001
                self.status = f"error: stop_trace: {e}"
        manifest = {
            "id": self.request_id,
            "status": self.status,
            "trace_dir": self.trace_dir,
            "start_step": self.start_step,
            "num_steps": self.num_steps,
            "finished_at": time.time(),
        }
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            _write_json(os.path.join(self.trace_dir, "manifest.json"),
                        manifest)
        except OSError:
            pass
        return manifest


class ProfilerSession:
    """Worker-side driver: polls the request file and the static window,
    owns at most one active capture. ``poll(step)`` is called once per
    loop iteration from the step loop's thread; ``stop()`` may be called
    from teardown paths — the lock keeps the two honest."""

    def __init__(self, request_path: str = "", static_dir: str = "",
                 static_start: int = 3, static_num: int = 3):
        self._lock = threading.Lock()
        self._request_path = request_path or os.environ.get(
            "DLROVER_TPU_PROFILE_REQUEST", "")
        self._static_dir = static_dir
        self._static_start = static_start
        self._static_num = static_num
        self._static_done = False
        self._active: Optional[ProfilerCapture] = None
        self._last_request_stat = None
        self._handled_id = -1
        # a respawned worker must not replay a request its predecessor
        # already served (the agent leaves the request file in place):
        # the completion manifest records the served id, so seed the
        # dedup watermark from it. A request with NO manifest was never
        # finished — re-running that one is the correct recovery.
        if self._request_path:
            done = read_profile_result(self._request_path)
            if done is not None:
                try:
                    self._handled_id = int(done.get("id", -1))
                except (TypeError, ValueError):
                    pass

    def poll(self, local_step: int) -> None:
        """Drive captures from the step loop. Cheap when idle: one stat
        of the request file (when configured) and two compares."""
        with self._lock:
            active = self._active
            if active is not None:
                if local_step - active.start_step >= active.num_steps:
                    self._finish_locked()
                return
            request = self._poll_request_locked()
            if request is not None:
                capture = ProfilerCapture(
                    request.get("dump_dir") or self._default_dump_dir(),
                    int(request.get("num_steps", 3) or 3),
                    request_id=int(request.get("id", 0)),
                    start_step=local_step,
                )
                logger.info("profiler: on-demand capture %d for %d "
                            "steps -> %s", capture.request_id,
                            capture.num_steps, capture.trace_dir)
                capture.start()
                self._active = capture
                return
            if (self._static_dir and not self._static_done
                    and local_step == self._static_start):
                self._static_done = True
                capture = ProfilerCapture(
                    self._static_dir, self._static_num,
                    request_id=0, start_step=local_step)
                logger.info("profiler: tracing %d steps to %s",
                            capture.num_steps, capture.trace_dir)
                capture.start()
                self._active = capture

    def stop(self) -> None:
        """Flush any active capture (step-loop teardown / step failure:
        a dangling jax trace session makes the NEXT start_trace raise)."""
        with self._lock:
            self._finish_locked()

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active is not None

    # -- internals (lock held) --------------------------------------------
    def _default_dump_dir(self) -> str:
        if self._request_path:
            return os.path.join(
                os.path.dirname(self._request_path) or ".", "profiles")
        return self._static_dir or "."

    def _finish_locked(self) -> None:
        if self._active is None:
            return
        capture, self._active = self._active, None
        manifest = capture.stop()
        logger.info("profiler: capture %d finished (%s)",
                    capture.request_id, manifest["status"])
        if capture.request_id and self._request_path:
            try:
                _write_json(self._request_path + ".done", manifest)
            except OSError:
                pass

    def _poll_request_locked(self) -> Optional[Dict[str, Any]]:
        if not self._request_path:
            return None
        try:
            st = os.stat(self._request_path)
        except OSError:
            return None
        # inode in the key (same contract as the drain-request channel):
        # every write is a tmp+rename, so a rewrite inside one coarse
        # mtime tick (1 s on some NFS) still changes the key — bare
        # mtime would skip that request forever
        stat_key = (st.st_ino, st.st_mtime_ns, st.st_size)
        if stat_key == self._last_request_stat:
            return None
        self._last_request_stat = stat_key
        try:
            with open(self._request_path) as f:
                request = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        if not isinstance(request, dict):
            return None
        request_id = int(request.get("id", 0) or 0)
        if request_id <= self._handled_id:
            return None  # replay of an already-served request
        self._handled_id = request_id
        return request
