"""Dependency-free metrics registry with Prometheus text exposition.

The substrate every component reports through (ROADMAP: "as fast as the
hardware allows" needs the elastic paths *measured*): thread-safe
Counter / Gauge / Histogram families with labels, a process-wide default
registry, and a tiny HTTP exporter the master serves `/metrics` from.

Deliberately stdlib-only — the agent and worker processes must be able
to import this without jax, grpc or any metrics client library; the
exposition format is the Prometheus text format 0.0.4 so any scraper
(or `curl`) can consume it.
"""

from __future__ import annotations

import bisect
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Sequence, Tuple

# Wide span: sub-ms lock waits up to multi-minute restores/compiles.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

# Prometheus naming rules. Enforced at family creation: names and label
# KEYS are interpolated verbatim into the exposition (only label VALUES
# are escaped), so one bad name — e.g. replayed from a remote
# TelemetryReport — would otherwise break every subsequent scrape of the
# whole endpoint.
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _fmt(value: float) -> str:
    """Prometheus number formatting: integers without the trailing .0.
    NaN must render (as 'NaN'), not raise — one poisoned gauge value
    must not take down every subsequent scrape."""
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    as_int = int(value)
    return str(as_int) if as_int == value else repr(value)


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _render_labels(names: Sequence[str], values: Sequence[str],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Child:
    """One labeled time series of a family."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._fn = None

    def set_function(self, fn: Callable[[], float]) -> None:
        """Collect-time callback (e.g. a SpeedMonitor query); wins over
        any stored value until `set` is called again."""
        with self._lock:
            self._fn = fn

    def get(self) -> float:
        with self._lock:
            fn = self._fn
            value = self._value
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — scrape must not break
                return value
        return value


class _HistogramChild:
    def __init__(self, lock: threading.Lock, buckets: Sequence[float]):
        self._lock = lock
        self._buckets = tuple(buckets)
        self._counts = [0] * (len(self._buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            # per-bucket counts; render() cumulates. bisect_left finds the
            # first bound >= value (le-bucket semantics); past the last
            # bound it lands on the +Inf slot.
            self._counts[bisect.bisect_left(self._buckets, value)] += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], Tuple[int, ...],
                                float, int]:
        with self._lock:
            return (self._buckets, tuple(self._counts), self._sum,
                    self._count)


class _Family:
    """A named metric family: children keyed by label values."""

    def __init__(self, name: str, help_text: str, kind: str,
                 labelnames: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for label in labelnames:
            if not _LABEL_NAME_RE.match(label):
                raise ValueError(
                    f"{name}: invalid label name {label!r}")
        self.name = name
        self.help = help_text
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self._lock, self._buckets)
        return _Child(self._lock)

    def labels(self, **labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
        return child

    def remove(self, **labels) -> bool:
        """Drop one labeled child (e.g. a per-worker gauge after the
        worker left the job — a stale series would keep ranking a dead
        rank in every scrape). Returns whether a child was removed."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            return self._children.pop(key, None) is not None

    # unlabeled conveniences -------------------------------------------
    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()")
        with self._lock:
            return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def get(self) -> float:
        return self._default().get()

    # rendering --------------------------------------------------------
    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            children = sorted(self._children.items())
        for key, child in children:
            if self.kind == "histogram":
                buckets, counts, total, count = child.snapshot()
                cumulative = 0
                for bound, n in zip(buckets + (float("inf"),), counts):
                    cumulative += n
                    labels = _render_labels(
                        self.labelnames, key, (("le", _fmt(bound)),))
                    lines.append(
                        f"{self.name}_bucket{labels} {cumulative}")
                labels = _render_labels(self.labelnames, key)
                lines.append(f"{self.name}_sum{labels} {_fmt(total)}")
                lines.append(f"{self.name}_count{labels} {count}")
            else:
                labels = _render_labels(self.labelnames, key)
                lines.append(f"{self.name}{labels} {_fmt(child.get())}")
        return "\n".join(lines)


class MetricsRegistry:
    """Thread-safe named registry; get-or-create per family."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, name: str, help_text: str, kind: str,
                       labelnames: Sequence[str],
                       buckets: Sequence[float] = DEFAULT_BUCKETS
                       ) -> _Family:
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = _Family(name, help_text, kind, labelnames,
                                 buckets)
                self._families[name] = family
            elif family.kind != kind or family.labelnames != tuple(
                    labelnames):
                raise ValueError(
                    f"metric {name!r} re-registered as {kind}"
                    f"{tuple(labelnames)} (was {family.kind}"
                    f"{family.labelnames})")
            return family

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._get_or_create(name, help_text, "counter", labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._get_or_create(name, help_text, "gauge", labelnames)

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._get_or_create(name, help_text, "histogram",
                                   labelnames, buckets)

    def render(self) -> str:
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        return "\n".join(f.render() for f in families) + "\n"

    def sample_values(self, prefixes: Sequence[str] = ()
                      ) -> list:
        """Point-in-time (name, labels-dict, value) tuples for every
        gauge/counter child whose family name starts with one of
        ``prefixes`` (all scalar families when empty) — the time-series
        collector's read path (obs/tsdb.py). Histograms are excluded:
        their cumulative buckets are not a samplable scalar. Child
        ``get()`` runs OUTSIDE the family lock (collect-time gauge
        callbacks may themselves take component locks)."""
        wanted = tuple(prefixes)
        with self._lock:
            families = [f for f in self._families.values()
                        if f.kind in ("gauge", "counter")
                        and (not wanted or f.name.startswith(wanted))]
        out = []
        for family in families:
            with family._lock:
                children = list(family._children.items())
            for key, child in children:
                try:
                    value = child.get()
                except Exception:  # noqa: BLE001 — one bad callback
                    # must not break the whole sampling tick
                    continue
                out.append((family.name,
                            dict(zip(family.labelnames, key)), value))
        return out

    def reset(self) -> None:
        """Tests only: drop every family."""
        with self._lock:
            self._families.clear()


_default_lock = threading.Lock()
_default_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide default registry."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


# --------------------------------------------------------------------------
# HTTP exporter (master-side /metrics endpoint)
# --------------------------------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set on the subclass by start_http_exporter

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        body = self.registry.render().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam the job log
        pass


def start_http_exporter(registry: Optional[MetricsRegistry] = None,
                        host: str = "0.0.0.0", port: int = 0
                        ) -> Tuple[ThreadingHTTPServer, int]:
    """Serve `registry.render()` on http://host:port/metrics in a daemon
    thread; returns (server, bound_port). port=0 picks a free port."""
    registry = registry or get_registry()
    handler = type("BoundMetricsHandler", (_MetricsHandler,),
                   {"registry": registry})
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="metrics-exporter")
    thread.start()
    return server, server.server_address[1]
