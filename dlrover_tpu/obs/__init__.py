"""Unified telemetry layer: metrics registry + lifecycle spans + flight
recorder.

One import wires the defaults: every finished span is recorded into the
process flight recorder and observed into the
``dlrover_tpu_span_duration_seconds`` histogram of the default registry.
Components then only need::

    from dlrover_tpu import obs

    with obs.span("rendezvous_round", {"round": 3}):
        ...
    obs.get_registry().counter("dlrover_tpu_rendezvous_rounds_total").inc()
    obs.get_flight_recorder().record_event("worker_spawn", rank=0)

See docs/observability.md for the metric catalog, span taxonomy and the
flight-recorder dump format.
"""

from __future__ import annotations

import threading

from dlrover_tpu.obs import device, mfu
from dlrover_tpu.obs.device import DeviceTelemetry
from dlrover_tpu.obs.flight_recorder import (
    FLIGHT_DIR_ENV,
    FlightRecorder,
    get_flight_recorder,
)
from dlrover_tpu.obs.goodput import (
    BADPUT_BUCKETS,
    BUCKETS,
    GoodputLedger,
    render_snapshot,
    snapshot_from_flight,
)
from dlrover_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    get_registry,
    start_http_exporter,
)
from dlrover_tpu.obs.profiler import (
    ProfilerCapture,
    ProfilerSession,
    read_profile_result,
    write_profile_request,
)
from dlrover_tpu.obs.spans import (
    Span,
    SpanExporter,
    add_span_sink,
    current_context,
    current_span,
    record_span,
    remove_span_sink,
    span,
)
from dlrover_tpu.obs.steptrace import (
    TRACE_PHASES,
    ClockSync,
    StepTraceRecorder,
    phase_seconds,
)
from dlrover_tpu.obs.timeline import StepTimeline, load_timeline
from dlrover_tpu.obs.tsdb import (
    TimeSeriesSidecar,
    TimeSeriesStore,
    TsdbCollector,
)

__all__ = [
    "BADPUT_BUCKETS",
    "BUCKETS",
    "DEFAULT_BUCKETS",
    "FLIGHT_DIR_ENV",
    "TRACE_PHASES",
    "ClockSync",
    "DeviceTelemetry",
    "FlightRecorder",
    "GoodputLedger",
    "MetricsRegistry",
    "ProfilerCapture",
    "ProfilerSession",
    "Span",
    "SpanExporter",
    "StepTimeline",
    "StepTraceRecorder",
    "TimeSeriesSidecar",
    "TimeSeriesStore",
    "TsdbCollector",
    "device",
    "add_span_sink",
    "current_context",
    "current_span",
    "get_flight_recorder",
    "get_registry",
    "load_timeline",
    "mfu",
    "phase_seconds",
    "publish_node_stats",
    "read_profile_result",
    "record_remote_spans",
    "record_span",
    "remove_span_sink",
    "render_snapshot",
    "snapshot_from_flight",
    "span",
    "start_http_exporter",
    "write_profile_request",
]

_defaults_lock = threading.Lock()
_defaults_installed = False


def _flight_recorder_sink(finished: Span) -> None:
    get_flight_recorder().record_span(finished)


def _metrics_sink(finished: Span) -> None:
    get_registry().histogram(
        "dlrover_tpu_span_duration_seconds",
        "Duration of lifecycle spans by name",
        labelnames=("span",),
    ).labels(span=finished.name).observe(finished.duration_s)


def _install_defaults() -> None:
    global _defaults_installed
    with _defaults_lock:
        if _defaults_installed:
            return
        add_span_sink(_flight_recorder_sink)
        add_span_sink(_metrics_sink)
        _defaults_installed = True


_install_defaults()


def record_remote_spans(spans, registry: MetricsRegistry = None) -> None:
    """Ingest span dicts that arrived from another process (agent →
    master telemetry path): append to the local flight recorder and feed
    the span-duration histogram, so the master's timeline and exposition
    cover the whole job. In a standalone (master+agent one-process) run
    the sender's spans were already recorded and observed locally — the
    recorder's span-id dedup gates the histogram too, so neither the
    timeline nor the duration series double-counts."""
    registry = registry or get_registry()
    recorder = get_flight_recorder()
    histogram = registry.histogram(
        "dlrover_tpu_span_duration_seconds",
        "Duration of lifecycle spans by name",
        labelnames=("span",),
    )
    for record in spans:
        if not isinstance(record, dict) or "name" not in record:
            continue
        if not recorder.record_span(record):
            continue
        try:
            histogram.labels(span=str(record["name"])).observe(
                float(record.get("duration_s", 0.0)))
        except (TypeError, ValueError):
            continue


def publish_node_stats(stats, registry: MetricsRegistry = None) -> None:
    """Per-node resource gauges from a NodeResourceStats-shaped object
    (node_id / node_type / cpu_percent / memory_mb / chip_stats). The
    single definition of these series — used by the agent's
    ResourceMonitor for its local registry and by the master servicer
    when the report arrives, so the two expositions cannot drift."""
    registry = registry or get_registry()
    # keyed by RANK when the sender provides one: node_id diverges from
    # rank after a relaunch, and every other per-worker series (the
    # servicer's step-report ingest, the diagnosis gauges) is
    # rank-keyed — a node_id key here would split one physical worker
    # into two dashboard rows the moment it relaunches
    rank = getattr(stats, "node_rank", -1)
    labels = {"node": str(rank if rank >= 0 else stats.node_id),
              "type": stats.node_type or "worker"}
    registry.gauge("dlrover_tpu_node_cpu_percent",
                   "Host CPU utilization reported by the agent",
                   labelnames=("node", "type")).labels(
        **labels).set(stats.cpu_percent)
    registry.gauge("dlrover_tpu_node_memory_mb",
                   "Host memory used reported by the agent",
                   labelnames=("node", "type")).labels(
        **labels).set(stats.memory_mb)
    if stats.chip_stats:
        # HBM series only when the backend actually reported memory
        # stats (any chip with a real total): a CPU backend's absent
        # memory_stats must not publish a forever-0 % series that
        # dashboards read as "plenty of headroom"
        if any(c.hbm_total_mb > 0 for c in stats.chip_stats):
            hbm = sum(c.hbm_used_mb for c in stats.chip_stats)
            registry.gauge("dlrover_tpu_node_hbm_used_mb",
                           "Sum of per-chip HBM in use",
                           labelnames=("node", "type")).labels(
                **labels).set(hbm)
            # the per-step peak watermark (obs/device.py via the chip
            # stats export): the transient IN-step peak, < 0 = unknown.
            # The export windows the lifetime-monotone counter (only a
            # RISE carries hbm_peak_mb), so a report without one means
            # the episode resolved — the gauge must follow the worst
            # current in-use instead of latching the old spike forever
            # (the series the time-series collector samples every tick)
            peaks = [c.hbm_peak_mb for c in stats.chip_stats
                     if getattr(c, "hbm_peak_mb", -1.0) >= 0.0]
            registry.gauge(
                "dlrover_tpu_node_hbm_peak_mb",
                "Worst per-chip HBM allocator peak watermark "
                "(in-step transient when it rose this window, else "
                "the worst current in-use)",
                labelnames=("node", "type")).labels(
                **labels).set(max(peaks) if peaks else
                              max(c.hbm_used_mb
                                  for c in stats.chip_stats))
        # duty < 0 is the "unknown" sentinel (agent/monitor.py
        # export_chip_stats only emits a value when it can derive the
        # proxy): averaging it in would fabricate utilization
        known = [c.duty_cycle_pct for c in stats.chip_stats
                 if c.duty_cycle_pct >= 0.0]
        if known:
            registry.gauge("dlrover_tpu_node_chip_duty_cycle_pct",
                           "Mean per-chip duty cycle",
                           labelnames=("node", "type")).labels(
                **labels).set(sum(known) / len(known))
