"""Per-step phase timeline: where did each training step's time go?

The step histogram says a step was slow; it cannot say *why*. The
timeline attributes every loop iteration to its phases — data-wait
(pulling the next batch from the host pipeline), h2d (host→device
transfer of the sharded batch), compute (train-step dispatch), plus the
occasional host_sync / checkpoint stalls — in a bounded ring the worker
exports as JSON beside its metrics file. The master's diagnosis rules
consume the windowed fractions (a worker whose data-wait fraction
dominates is pipeline-bound, not a hardware straggler), and
``tools/diagnose.py`` renders the ring as a per-step breakdown.

Recording is on the hot path: one ``record()`` per step must cost
microseconds (acceptance: < 1 % of step time on the CPU bench), so a
record is a dict append under a plain lock — no I/O, no metrics. The
periodic ``export()`` (report-interval cadence) does the JSON write,
atomically, so the agent-side reader never sees a torn file.

stdlib-only by design (imported by the worker process beside jax, and
by agent/tools without it).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# canonical phase order (rendering + fraction math); "other" is the
# residual of total_s not covered by an explicit phase
PHASES = ("data_wait", "h2d", "compute", "host_sync", "checkpoint")

TIMELINE_VERSION = 1


class StepTimeline:
    """Bounded ring of per-step phase attributions."""

    def __init__(self, capacity: int = 256, role: str = "worker",
                 rank: int = -1):
        self._lock = threading.Lock()
        self._steps: deque = deque(maxlen=capacity)
        self._role = role
        self._rank = rank

    def record(self, step: int, total_s: float,
               **phases: float) -> None:
        """One finished loop iteration. ``phases`` are seconds per phase
        (unknown phases are kept — the format is open); the residual
        lands under "other"."""
        known = sum(phases.values())
        entry = {"step": int(step), "total_s": float(total_s),
                 "phases": {k: float(v) for k, v in phases.items()}}
        residual = total_s - known
        if residual > 1e-9:
            entry["phases"]["other"] = residual
        with self._lock:
            self._steps.append(entry)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._steps)

    def window_stats(self, last_n: int = 0) -> Dict[str, float]:
        """Mean step time + per-phase fraction over the last ``last_n``
        records (0 = whole ring). ``data_wait_fraction`` is -1.0 when no
        samples exist — callers must not mistake "no data" for "0 %"."""
        with self._lock:
            steps = list(self._steps)
        if last_n > 0:
            steps = steps[-last_n:]
        if not steps:
            return {"samples": 0, "mean_step_s": 0.0,
                    "data_wait_fraction": -1.0}
        total = sum(e["total_s"] for e in steps)
        stats: Dict[str, float] = {
            "samples": len(steps),
            "mean_step_s": total / len(steps),
        }
        if total > 0:
            phase_totals: Dict[str, float] = {}
            for entry in steps:
                for name, value in entry["phases"].items():
                    phase_totals[name] = phase_totals.get(name, 0.0) + value
            for name, value in phase_totals.items():
                stats[f"{name}_fraction"] = value / total
        stats.setdefault("data_wait_fraction", -1.0 if total <= 0 else 0.0)
        return stats

    # -- export / parse ----------------------------------------------------
    def export(self, path: str, last_n: int = 0) -> bool:
        """Atomically write the ring as JSON (the agent/diagnose reader's
        contract). ``last_n`` > 0 writes only the newest N records — the
        hot loop's report-interval exports serialize a tail (a full
        256-record dump costs milliseconds, which would blow the < 1 %
        per-step overhead budget on fast steps); teardown exports the
        whole ring. Never raises — a full disk must not kill the step
        loop."""
        steps = self.snapshot()
        if last_n > 0:
            steps = steps[-last_n:]
        payload = {
            "version": TIMELINE_VERSION,
            "role": self._role,
            "rank": self._rank,
            "pid": os.getpid(),
            "exported_at": time.time(),
            "steps": steps,
        }
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            return True
        except OSError:
            return False


def load_timeline(path: str) -> Optional[Dict[str, Any]]:
    """Parse an exported timeline file; None on missing/corrupt (readers
    poll while the worker is mid-flight — absence is normal)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("steps"), list):
        return None
    return payload
