"""Per-step trace records + NTP-style clock alignment (worker side).

StepTimeline answers "where did the window's time go"; steptrace answers
"why was *step N* slow, and who gated it". Each finished step emits one
compact record — monotonic phase-boundary offsets for the classic phases
(data_wait / h2d / compute / host_sync / checkpoint) plus the cross-slice
decomposition SliceGradSync exposes (grads-ready, local-post,
per-peer-header-observed, last-peer wait, apply) — a few hundred bytes,
batched over the existing TelemetryReport channel with a bounded
drop-oldest ring, exactly like SpanExporter.

Records from different hosts compose into one fleet waterfall because
every record is stamped with the worker's current clock offset estimate
against the master (`ClockSync`): an NTP-style midpoint probe over the
existing RPC path — offset = server_ts − (t0+t1)/2, uncertainty =
RTT/2 — refreshed periodically, with a drift allowance aging the
uncertainty so a stale estimate still *bounds* the true offset.

The master-side join/critical-path solve lives in
``dlrover_tpu.master.steptrace``; the record format here is the wire
contract between the two.

stdlib-only by design (imported by the worker beside jax, by tools and
tests without it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

STEPTRACE_VERSION = 1

# canonical phase order for rendering (a record may carry any subset);
# local_post / cross_slice_wait / apply are the SliceGradSync
# decomposition, the rest mirror obs.timeline.PHASES
TRACE_PHASES = (
    "data_wait", "h2d", "compute", "local_post", "cross_slice_wait",
    "apply", "host_sync", "checkpoint",
)


class ClockSync:
    """NTP-style offset estimator over the master RPC path.

    ``offset`` approximates ``master_wall - local_wall``: one probe wraps
    a single round trip — ``t0 = wall(); server_ts = probe_fn();
    t1 = wall()`` — and the midpoint estimate
    ``server_ts - (t0 + t1) / 2`` errs by at most half the RTT under
    arbitrarily asymmetric request/response latency, so ``(t1 - t0) / 2``
    is a sound uncertainty bound. `estimate()` returns the sample whose
    *aged* bound (raw bound + DRIFT_PPM allowance per second since the
    probe) is smallest, so the stamped uncertainty keeps bounding the
    true offset as local oscillator drift accumulates between refreshes.

    ``probe_fn`` returns the server's wall clock (seconds) or raises /
    returns <= 0 on failure; probes are droppable by contract — a failed
    probe only ages the previous estimate. Clocks are injectable for the
    skew/drift/asymmetric-latency property tests.
    """

    # generous oscillator drift allowance (typical quartz is < 50 ppm;
    # 200 keeps the bound sound on thermally stressed hosts)
    DRIFT_PPM = 200.0

    def __init__(self, probe_fn: Optional[Callable[[], float]] = None,
                 wall: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic,
                 window: int = 8):
        self._probe_fn = probe_fn
        self._wall = wall
        self._mono = mono
        self._lock = threading.Lock()
        # (offset_s, err_s, mono_at) — newest last, bounded
        self._samples: deque = deque(maxlen=max(1, window))
        self._probes = 0
        self._failures = 0
        self._last_probe_mono = float("-inf")

    def probe(self) -> bool:
        """One synchronous round trip; False on failure (estimate keeps
        the previous samples)."""
        fn = self._probe_fn
        if fn is None:
            return False
        t0 = self._wall()
        try:
            server_ts = float(fn())
        except Exception:  # noqa: BLE001 — telemetry must never raise
            with self._lock:
                self._failures += 1
            return False
        t1 = self._wall()
        with self._lock:
            self._last_probe_mono = self._mono()
            if server_ts <= 0.0 or t1 < t0:
                # server declined (no master-side support) or the local
                # wall clock stepped backwards mid-probe: unusable
                self._failures += 1
                return False
            self._samples.append((server_ts - 0.5 * (t0 + t1),
                                  0.5 * (t1 - t0), self._mono()))
            self._probes += 1
            return True

    def maybe_probe(self, interval_s: float) -> bool:
        """Rate-limited refresh for hot-loop call sites: probes only when
        ``interval_s`` has elapsed since the last attempt (success or
        not — a dead master must not turn every step into an RPC)."""
        with self._lock:
            due = self._mono() - self._last_probe_mono >= interval_s
        return self.probe() if due else False

    def estimate(self) -> Tuple[float, float]:
        """``(offset_s, err_s)``: the sample with the smallest aged
        uncertainty. ``err_s`` is -1.0 before any successful probe ("no
        data", the repo-wide sentinel) with offset 0.0 — records from an
        unaligned worker still compose within their own host."""
        with self._lock:
            now = self._mono()
            samples = list(self._samples)
        if not samples:
            return 0.0, -1.0
        aged = [(off, err + max(0.0, now - at) * self.DRIFT_PPM * 1e-6)
                for off, err, at in samples]
        return min(aged, key=lambda s: s[1])

    def stats(self) -> Dict[str, float]:
        offset, err = self.estimate()
        with self._lock:
            return {"probes": self._probes, "failures": self._failures,
                    "offset_s": offset, "err_s": err,
                    "samples": len(self._samples)}


class StepTraceRecorder:
    """Bounded drop-oldest buffer of per-step trace records.

    ``record()`` is on the hot path (one call per step): it builds one
    small dict and appends under a plain lock — no I/O, no RPC
    (acceptance: < 1 % of a 10 ms step, like StepTimeline). Shipping
    happens at report cadence via ``flush_to`` over the TelemetryReport
    channel and is droppable by contract.

    Record format (the wire contract with master/steptrace.py)::

        {"v": 1, "step": int, "gen": int, "slice": int, "rank": int,
         "t0": local wall-clock at step start,
         "off": clock offset estimate (master - local, s),
         "err": offset uncertainty bound (s, -1.0 = unaligned),
         "phases": [[name, start_offset_s, duration_s], ...],
         "peers": {slice_id: header_observed_offset_s, ...}}  # optional

    Phase offsets are relative to ``t0``; the master aligns records by
    ``t0 + off`` into one fleet timeline.
    """

    def __init__(self, capacity: int = 512, rank: int = -1,
                 slice_id: int = -1,
                 clock_sync: Optional[ClockSync] = None):
        self._lock = threading.Lock()
        self._capacity = max(1, capacity)
        self._records: List[Dict[str, Any]] = []
        self._dropped = 0
        self._rank = int(rank)
        self._slice_id = int(slice_id)
        self._clock_sync = clock_sync

    def set_identity(self, rank: Optional[int] = None,
                     slice_id: Optional[int] = None) -> None:
        """Rank/slice become known (or change) after a rendezvous."""
        with self._lock:
            if rank is not None:
                self._rank = int(rank)
            if slice_id is not None:
                self._slice_id = int(slice_id)

    def record(self, step: int, generation: int, t0: float,
               phases: Iterable[Tuple[str, float, float]],
               peers: Optional[Dict[int, float]] = None) -> None:
        """One finished step. ``t0`` is the local wall clock at step
        start; ``phases`` are ``(name, start_offset_s, duration_s)``
        relative to it; ``peers`` maps peer slice id to the offset at
        which its gradient header was observed."""
        if self._clock_sync is not None:
            off, err = self._clock_sync.estimate()
        else:
            off, err = 0.0, -1.0
        entry: Dict[str, Any] = {
            "v": STEPTRACE_VERSION,
            "step": int(step),
            "gen": int(generation),
            "slice": self._slice_id,
            "rank": self._rank,
            "t0": float(t0),
            "off": round(off, 6),
            "err": round(err, 6),
            "phases": [[str(n), round(float(s), 6), round(float(d), 6)]
                       for n, s, d in phases],
        }
        if peers:
            entry["peers"] = {str(k): round(float(v), 6)
                              for k, v in peers.items()}
        with self._lock:
            self._records.append(entry)
            overflow = len(self._records) - self._capacity
            if overflow > 0:
                del self._records[:overflow]
                self._dropped += overflow

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            batch, self._records = self._records, []
            return batch

    def flush_to(self, client) -> None:
        """Drain and ship via ``client.report_telemetry(steptrace=...)``.
        Telemetry is droppable by contract: every failure is swallowed
        (the batch is lost, the caller's step loop must never be)."""
        batch = self.drain()
        if not batch:
            return
        try:
            client.report_telemetry(steptrace=batch)
        except Exception:  # noqa: BLE001 — droppable by contract
            pass

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped


def phase_seconds(record: Dict[str, Any]) -> Dict[str, float]:
    """Total seconds per phase name in one record (a phase may appear in
    several segments). Malformed segments are skipped, not raised — the
    wire is telemetry."""
    totals: Dict[str, float] = {}
    for seg in record.get("phases") or []:
        try:
            name, _, dur = seg[0], float(seg[1]), float(seg[2])
        except (TypeError, ValueError, IndexError):
            continue
        totals[str(name)] = totals.get(str(name), 0.0) + max(0.0, dur)
    return totals
