"""Lifecycle spans: explicit begin/end timing with parent propagation.

The elastic paths (rendezvous, scale decisions, re-lower/compile,
checkpoint save/restore) only fire during elasticity — a sampling
profiler never sees them. Spans make them first-class: a `span(...)`
context manager times a named region, nests under the thread's current
span, and on completion fans out to registered sinks (the flight
recorder, the duration histogram, a publisher batching spans to the
master).

Cross-process parenting: `current_context()` serializes the active
span's identity into a small dict that travels inside a control-plane
message; the receiving side passes it as ``parent=`` so the master's
rendezvous span and the agent's join span share one trace.

stdlib-only by design (imported by agent/worker/master alike).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed region. Create via the `span(...)` context manager."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ts",
                 "end_ts", "duration_s", "attrs", "status", "pid",
                 "_start_mono")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str = "",
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ts = time.time()
        self.end_ts = 0.0
        self.duration_s = 0.0
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.status = "ok"
        self.pid = os.getpid()
        self._start_mono = time.monotonic()

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def finish(self, status: str = "ok") -> None:
        self.end_ts = time.time()
        self.duration_s = time.monotonic() - self._start_mono
        self.status = status

    def context(self) -> Dict[str, str]:
        """The propagation payload a child (possibly in another process)
        parents under."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts": self.start_ts,
            "end_ts": self.end_ts,
            "duration_s": round(self.duration_s, 6),
            "status": self.status,
            "pid": self.pid,
            "attrs": self.attrs,
        }


_tls = threading.local()

_sink_lock = threading.Lock()
_sinks: List[Callable[[Span], None]] = []


def add_span_sink(sink: Callable[[Span], None]) -> None:
    with _sink_lock:
        if sink not in _sinks:
            _sinks.append(sink)


def remove_span_sink(sink: Callable[[Span], None]) -> None:
    with _sink_lock:
        if sink in _sinks:
            _sinks.remove(sink)


def _dispatch(finished: Span) -> None:
    with _sink_lock:
        sinks = list(_sinks)
    for sink in sinks:
        try:
            sink(finished)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass


def _stack() -> List[Span]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = []
        _tls.stack = stack
    return stack


def current_span() -> Optional[Span]:
    stack = _stack()
    return stack[-1] if stack else None


def current_context() -> Optional[Dict[str, str]]:
    """Serialized identity of the active span for cross-process
    propagation (None outside any span)."""
    active = current_span()
    return active.context() if active else None


def _resolve_parent(parent: Optional[Dict[str, str]],
                    stack: List[Span]) -> tuple:
    """(trace_id, parent_id): explicit remote context wins, else the
    thread's current span, else a fresh trace."""
    if parent:
        return parent.get("trace_id") or _new_id(), parent.get(
            "span_id", "")
    if stack:
        return stack[-1].trace_id, stack[-1].span_id
    return _new_id(), ""


@contextmanager
def span(name: str, attrs: Optional[Dict[str, Any]] = None,
         parent: Optional[Dict[str, str]] = None):
    """Time a region. Nests under the thread's current span unless an
    explicit remote ``parent`` context (from `current_context()` on the
    other side) is given. An exception inside marks status="error" and
    re-raises."""
    stack = _stack()
    trace_id, parent_id = _resolve_parent(parent, stack)
    current = Span(name, trace_id, _new_id(), parent_id, attrs)
    stack.append(current)
    try:
        yield current
        current.finish("ok")
    except BaseException:
        current.finish("error")
        raise
    finally:
        stack.pop()
        _dispatch(current)


def record_span(name: str, duration_s: float,
                attrs: Optional[Dict[str, Any]] = None,
                parent: Optional[Dict[str, str]] = None,
                status: str = "ok") -> Span:
    """Record an already-measured region as a finished span (for paths
    that know their start retroactively, e.g. a rendezvous round timed
    from its first join)."""
    trace_id, parent_id = _resolve_parent(parent, _stack())
    finished = Span(name, trace_id, _new_id(), parent_id, attrs)
    now = time.time()
    finished.start_ts = now - duration_s
    finished.end_ts = now
    finished.duration_s = float(duration_s)
    finished.status = status
    _dispatch(finished)
    return finished


class SpanExporter:
    """A sink that batches finished spans for shipping to the master.

    Bounded: when more than ``capacity`` spans accumulate between
    flushes, the oldest are dropped (and counted) — a wedged master must
    not grow worker memory."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._spans: List[Dict[str, Any]] = []
        self._dropped = 0

    def __call__(self, finished: Span) -> None:
        with self._lock:
            self._spans.append(finished.to_dict())
            overflow = len(self._spans) - self._capacity
            if overflow > 0:
                del self._spans[:overflow]
                self._dropped += overflow

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            batch, self._spans = self._spans, []
            return batch

    def flush_to(self, client) -> None:
        """Drain and ship to the master via
        ``client.report_telemetry(spans=...)``. Telemetry is droppable
        by contract: every failure is swallowed (the batch is lost, the
        caller's work must never be)."""
        spans = self.drain()
        if not spans:
            return
        try:
            client.report_telemetry(spans=spans)
        except Exception:  # noqa: BLE001 — droppable by contract
            pass

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped
