"""Fleet time-series plane: the master's bounded, multi-resolution memory.

Every observability surface before this module was instantaneous —
Prometheus gauges, point-in-time goodput snapshots, a monitor tick that
samples between steps — so the master could not answer "what changed in
the last ten minutes" and nothing could check the planner's predictions
against history. :class:`TimeSeriesStore` is that memory: labeled series
with a raw ring plus downsampled tiers (count/sum/min/max/last per
aligned bucket), bounded by construction (a week-long fleet cannot grow
it), queried windowed-and-aligned over the ``TimeSeriesQuery`` RPC and
rendered live by ``tools/top.py``.

Deliberately stdlib-only (the jax-free master owns the store; tools and
tests import it bare) with an injectable clock — retention and
downsampling are tested property-style over fake time, not wall-clock
sleeps.

Persistence: the downsampled tiers ride a checksummed sidecar file
beside the PR 3 snapshot store (:class:`TimeSeriesSidecar`,
``tsdb-state.json`` in the master state dir) written on the collector's
flush cadence + graceful stop — deliberately NOT inside the snapshot
export, whose ``save_if_changed`` dedup must not churn a new version
every time a background sample lands. A restarted master — or a
promoted hot standby sharing the state dir — reloads it, so fleet
history survives the master. The raw ring deliberately does not
persist: sub-tier-resolution points describe the dead incarnation's
last seconds, and the first tier covers them.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import default_logger as logger

TSDB_VERSION = 1
SIDECAR_NAME = "tsdb-state.json"

# raw points per series (report-cadence feeds; ~20 min at 5 s)
RAW_CAPACITY = 240
# buckets per downsampled tier per series
TIER_CAPACITY = 180
# tier resolutions, finest first: 180 buckets give 30 min / 3 h / 15 h
# of aligned history per tier — "the last ten minutes" answers from the
# finest tier, "since yesterday" from the coarsest
DEFAULT_TIERS = (10.0, 60.0, 300.0)
# distinct labeled series retained; past it, NEW series are dropped
# (counted) — an unbounded label space must not grow the master
MAX_SERIES = 512

# bucket layout: [start_ts, count, sum, min, max, last]
_B_TS, _B_COUNT, _B_SUM, _B_MIN, _B_MAX, _B_LAST = range(6)


def _labels_key(labels: Optional[Dict[str, str]]
                ) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Tier:
    """One downsampled resolution: a bounded ring of aligned buckets."""

    def __init__(self, resolution_s: float,
                 capacity: int = TIER_CAPACITY):
        self.resolution_s = float(resolution_s)
        self.buckets: deque = deque(maxlen=capacity)

    def ingest(self, ts: float, value: float) -> None:
        start = (ts // self.resolution_s) * self.resolution_s
        if self.buckets:
            last = self.buckets[-1]
            if last[_B_TS] == start:
                last[_B_COUNT] += 1
                last[_B_SUM] += value
                last[_B_MIN] = min(last[_B_MIN], value)
                last[_B_MAX] = max(last[_B_MAX], value)
                last[_B_LAST] = value
                return
            if start < last[_B_TS]:
                # a late point behind the open bucket (clock skew on a
                # remote feed): fold into its bucket when still retained,
                # drop otherwise — never un-order the ring
                for bucket in reversed(self.buckets):
                    if bucket[_B_TS] == start:
                        bucket[_B_COUNT] += 1
                        bucket[_B_SUM] += value
                        bucket[_B_MIN] = min(bucket[_B_MIN], value)
                        bucket[_B_MAX] = max(bucket[_B_MAX], value)
                        return
                    if bucket[_B_TS] < start:
                        break
                return
        self.buckets.append([start, 1, value, value, value, value])

    def export(self) -> List[List[float]]:
        return [list(b) for b in self.buckets]

    def restore(self, buckets: Sequence[Sequence[float]]) -> None:
        self.buckets.clear()
        for raw in buckets:
            if isinstance(raw, (list, tuple)) and len(raw) == 6:
                self.buckets.append([float(x) for x in raw])


class _Series:
    def __init__(self, name: str, labels: Dict[str, str],
                 tiers: Sequence[float], raw_capacity: int,
                 tier_capacity: int):
        self.name = name
        self.labels = dict(labels)
        self.raw: deque = deque(maxlen=raw_capacity)
        self.tiers = [_Tier(r, tier_capacity) for r in tiers]

    def ingest(self, ts: float, value: float) -> None:
        self.raw.append((ts, value))
        for tier in self.tiers:
            tier.ingest(ts, value)


class TimeSeriesStore:
    """Bounded multi-resolution store of labeled numeric series.

    Thread-safe: fed from servicer threads (step reports) and the
    collector's sampling thread, read by query RPCs and exports —
    everything goes through one lock; ``ingest`` is an append plus one
    bucket update per tier (microseconds; the overhead-bound test in
    tests/test_fleet_tsdb.py pins it under 1 % of a CPU bench step).
    """

    def __init__(self, tiers: Sequence[float] = DEFAULT_TIERS,
                 raw_capacity: int = RAW_CAPACITY,
                 tier_capacity: int = TIER_CAPACITY,
                 max_series: int = MAX_SERIES,
                 clock: Callable[[], float] = time.time):
        if not tiers:
            raise ValueError("at least one downsampled tier is required")
        self._tiers = tuple(sorted(float(t) for t in tiers))
        self._raw_capacity = int(raw_capacity)
        self._tier_capacity = int(tier_capacity)
        self._max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self._dropped_series = 0
        # graftlint: ephemeral(stats tally reported in query stats; not history)
        self._ingested = 0

    # -- write path --------------------------------------------------------
    def ingest(self, name: str, value: float,
               labels: Optional[Dict[str, str]] = None,
               ts: Optional[float] = None) -> bool:
        """Append one point. Returns False when the series cap refused a
        NEW series (existing series always ingest)."""
        try:
            value = float(value)
        except (TypeError, ValueError):
            return False
        if value != value:           # NaN poisons min/max aggregates
            return False
        key = (str(name), _labels_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self._max_series:
                    self._dropped_series += 1
                    return False
                series = _Series(key[0], dict(key[1]), self._tiers,
                                 self._raw_capacity,
                                 self._tier_capacity)
                self._series[key] = series
            series.ingest(self._clock() if ts is None else float(ts),
                          value)
            self._ingested += 1
        return True

    # -- read path ---------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return sorted({key[0] for key in self._series})

    def tiers(self) -> List[Dict[str, float]]:
        """The store's resolution ladder (raw + downsampled), with the
        per-series coverage each tier can answer."""
        with self._lock:
            tiers = self._tiers
            raw_cap, tier_cap = self._raw_capacity, self._tier_capacity
        out = [{"resolution_s": 0.0, "capacity": raw_cap,
                "kind": "raw"}]
        for res in tiers:
            out.append({"resolution_s": res,
                        "capacity": tier_cap,
                        "coverage_s": res * tier_cap,
                        "kind": "downsampled"})
        return out

    def _match_locked(self, name: str,
                      labels: Optional[Dict[str, str]]) -> List[_Series]:
        """Exact name (or prefix when it ends with ``*``) + label-subset
        match, deterministic order."""
        want = _labels_key(labels)
        prefix = name.endswith("*")
        stem = name[:-1] if prefix else name
        out = []
        for key in sorted(self._series):
            if (key[0].startswith(stem) if prefix else key[0] == stem):
                if all(pair in key[1] for pair in want):
                    out.append(self._series[key])
        return out

    def _pick_resolution(self, window_s: float, resolution_s: float,
                         series: Optional[_Series] = None,
                         start: float = 0.0) -> float:
        """0 = auto: raw when the series' raw ring actually spans the
        window, else the finest tier that covers it; an explicit
        request snaps UP to the nearest available tier (asking for
        30 s granularity must not silently answer 10 s buckets the
        caller will mis-align)."""
        if resolution_s > 0:
            for res in self._tiers:
                if res >= resolution_s - 1e-9:
                    return res
            return self._tiers[-1]
        if window_s <= 0:
            # unbounded read: raw only when the ring actually reaches
            # back to the oldest retained history. After a restart or
            # standby promotion the raw ring deliberately restarts
            # empty while the restored tiers hold hours — answering
            # raw there would read as "history lost"; a wrapped ring
            # similarly hides everything the tiers still retain.
            if series is None:
                return 0.0
            oldest = min((t.buckets[0][_B_TS] for t in series.tiers
                          if t.buckets), default=None)
            if oldest is None:
                return 0.0
            if series.raw and series.raw[0][0] <= oldest + self._tiers[-1]:
                return 0.0
            # finest tier that still reaches the oldest retained data.
            # Tiers align to different grids, so the coarsest bucket's
            # START can precede a finer tier's by up to one coarse
            # bucket with no history lost — the slack is the coarsest
            # resolution, not each tier's own.
            for tier in series.tiers:
                if tier.buckets and tier.buckets[0][_B_TS] \
                        <= oldest + self._tiers[-1]:
                    return tier.resolution_s
            return self._tiers[-1]
        if series is not None and series.raw \
                and series.raw[0][0] <= start:
            return 0.0
        for res in self._tiers:
            if res * self._tier_capacity >= window_s:
                return res
        return self._tiers[-1]

    def query(self, name: str,
              labels: Optional[Dict[str, str]] = None,
              window_s: float = 0.0,
              resolution_s: float = 0.0,
              end_ts: Optional[float] = None) -> List[Dict[str, Any]]:
        """Windowed, aligned read. Each result dict:
        ``{"name", "labels", "resolution_s", "points"}`` where points
        are ``[ts, value]`` for raw reads and
        ``[bucket_start, mean, min, max, count, last]`` for tier reads
        (``last`` = the newest value that landed in the bucket — what a
        "current value" tile should show; the mean of a ramping open
        bucket is history, not now), ascending, bucket starts aligned
        to the resolution grid."""
        now = self._clock() if end_ts is None else float(end_ts)
        start = now - window_s if window_s > 0 else float("-inf")
        with self._lock:
            matched = self._match_locked(name, labels)
            out = []
            for series in matched:
                chosen = self._pick_resolution(window_s, resolution_s,
                                               series=series,
                                               start=start)
                if chosen <= 0.0:
                    points = [[ts, value] for ts, value in series.raw
                              if start <= ts <= now]
                else:
                    tier = next(t for t in series.tiers
                                if t.resolution_s == chosen)
                    points = [
                        [b[_B_TS],
                         b[_B_SUM] / b[_B_COUNT] if b[_B_COUNT] else 0.0,
                         b[_B_MIN], b[_B_MAX], int(b[_B_COUNT]),
                         b[_B_LAST]]
                        for b in tier.buckets
                        if start <= b[_B_TS] <= now]
                out.append({"name": series.name,
                            "labels": dict(series.labels),
                            "resolution_s": chosen,
                            "points": points})
        return out

    def query_payload(self, name: str = "",
                      labels: Optional[Dict[str, str]] = None,
                      window_s: float = 0.0,
                      resolution_s: float = 0.0) -> Dict[str, Any]:
        """The RPC answer shape (master/servicer.py TimeSeriesQuery):
        matched series plus the tier ladder and the store's bounded-
        memory stats; an empty ``name`` lists series names only."""
        payload: Dict[str, Any] = {
            "version": TSDB_VERSION,
            "tiers": self.tiers(),
            "stats": self.stats(),
        }
        if name:
            payload["series"] = self.query(name, labels=labels,
                                           window_s=window_s,
                                           resolution_s=resolution_s)
        else:
            payload["names"] = self.names()
        return payload

    # -- bounded memory ----------------------------------------------------
    def memory_bound_bytes(self) -> int:
        """The hard cap the store can never exceed, from its
        construction parameters (asserted in tests)."""
        with self._lock:
            return self._memory_bound_locked()

    def _memory_bound_locked(self) -> int:
        """(lock held) per-series raw + tier floats at 8 bytes plus a
        generous per-point/bucket python overhead factor."""
        per_series = (self._raw_capacity * 2
                      + len(self._tiers) * self._tier_capacity * 6)
        # ~56 bytes per boxed float + list/tuple overhead, rounded up
        return self._max_series * per_series * 64

    def stats(self) -> Dict[str, int]:
        with self._lock:
            points = sum(len(s.raw) for s in self._series.values())
            buckets = sum(len(t.buckets) for s in self._series.values()
                          for t in s.tiers)
            return {
                "series": len(self._series),
                "max_series": self._max_series,
                "raw_points": points,
                "tier_buckets": buckets,
                "ingested_total": self._ingested,
                "dropped_series": self._dropped_series,
                "approx_bytes": (points * 2 + buckets * 6) * 64,
                "memory_bound_bytes": self._memory_bound_locked(),
            }

    # -- persistence (downsampled tiers only) ------------------------------
    def export_state(self) -> Dict[str, Any]:
        with self._lock:
            series = []
            for key in sorted(self._series):
                s = self._series[key]
                series.append({
                    "name": s.name,
                    "labels": dict(s.labels),
                    "tiers": {str(t.resolution_s): t.export()
                              for t in s.tiers},
                })
            return {"version": TSDB_VERSION,
                    "tiers": list(self._tiers),
                    "series": series}

    def restore_state(self, state: Dict[str, Any]) -> int:
        """Rehydrate downsampled history (raw rings restart empty — the
        dead master's sub-tier points are covered by the first tier).
        Series past the cap are dropped, counted. Returns the number of
        series restored."""
        if not isinstance(state, dict):
            return 0
        restored = 0
        for record in state.get("series", []):
            if not isinstance(record, dict) or not record.get("name"):
                continue
            labels = record.get("labels") or {}
            key = (str(record["name"]), _labels_key(labels))
            with self._lock:
                series = self._series.get(key)
                if series is None:
                    if len(self._series) >= self._max_series:
                        self._dropped_series += 1
                        continue
                    series = _Series(key[0], dict(key[1]), self._tiers,
                                     self._raw_capacity,
                                     self._tier_capacity)
                    self._series[key] = series
                tiers = record.get("tiers") or {}
                for tier in series.tiers:
                    buckets = tiers.get(str(tier.resolution_s))
                    if buckets:
                        tier.restore(buckets)
                restored += 1
        return restored


class TimeSeriesSidecar:
    """Checksummed atomic persistence for the store's downsampled tiers,
    one file beside the PR 3 snapshots (same atomic tmp+rename + sha256
    discipline; a torn write leaves the previous file, a corrupt one
    reads as absent — history loss is bounded by the flush cadence,
    never a crashed restore)."""

    def __init__(self, directory: str):
        self._path = os.path.join(directory, SIDECAR_NAME)
        os.makedirs(directory, exist_ok=True)

    @property
    def path(self) -> str:
        return self._path

    @staticmethod
    def _checksum(payload: str) -> str:
        return hashlib.sha256(payload.encode("utf-8")).hexdigest()

    def save(self, store: TimeSeriesStore,
             gate: Optional[Callable[[], bool]] = None) -> bool:
        # fence check at the writer itself, not only in the collector's
        # flush cadence: a deposed master's direct save must not clobber
        # the promoted master's history file either
        if gate is not None and gate():
            return False
        state = store.export_state()
        payload = json.dumps(state, sort_keys=True,
                             separators=(",", ":"))
        wrapper = {"version": TSDB_VERSION,
                   "checksum": self._checksum(payload),
                   "state": state}
        try:
            # pid+thread unique: a stop-time flush racing the cadence
            # flush must not interleave writes into one tmp file and
            # rename torn JSON over the history
            tmp = (f"{self._path}.{os.getpid()}"
                   f".{threading.get_ident()}.tmp")
            with open(tmp, "w") as f:
                json.dump(wrapper, f)
            os.replace(tmp, self._path)
            return True
        except OSError:
            return False

    def load(self, store: TimeSeriesStore) -> int:
        """Restore into ``store``; 0 on missing/corrupt (absence is the
        fresh-job normal, corruption is logged by the caller via the
        return value)."""
        try:
            with open(self._path) as f:
                wrapper = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return 0
        state = wrapper.get("state")
        if not isinstance(state, dict):
            return 0
        payload = json.dumps(state, sort_keys=True,
                             separators=(",", ":"))
        if self._checksum(payload) != wrapper.get("checksum"):
            return 0
        return store.restore_state(state)


# gauge/counter families the collector samples into the store each tick
# (the "fleet vitals" allowlist — an unbounded registry must not become
# an unbounded series space; per-rank device truth additionally arrives
# through the servicer's step-report ingest)
COLLECTED_PREFIXES = (
    "dlrover_tpu_training_",            # step / steps_s / tokens_s / mfu
    "dlrover_tpu_slice_",               # per-slice rollups + degraded
    "dlrover_tpu_worker_straggler_score",
    "dlrover_tpu_worker_data_wait_fraction",
    # dlrover_tpu_worker_mfu is deliberately NOT sampled here: the
    # servicer already ingests it per step report under {node} —
    # resampling the diagnosis registry gauge (labeled node+slice)
    # would store a second, differently-labeled series per rank
    # (double the 512-cap cost, ambiguous label-subset queries)
    "dlrover_tpu_node_hbm_",            # used + peak watermark MB
    "dlrover_tpu_node_cpu_percent",
    "dlrover_tpu_goodput_",
    "dlrover_tpu_elasticity_events_total",
    "dlrover_tpu_capacity_offers_",     # open gauge + lifecycle counter
    "dlrover_tpu_autoscale_",           # decisions + quarantined classes
)

# the dashboard's series set — the SINGLE source tools/top.py queries
# live and flight_snapshot embeds in the master's flight dump, so the
# --flight render never silently misses a column the live one shows
DASHBOARD_SERIES = (
    "dlrover_tpu_training_steps_per_second",
    "dlrover_tpu_training_mfu",
    "dlrover_tpu_training_global_step",
    "dlrover_tpu_goodput_fraction",
    "dlrover_tpu_slice_steps_per_second",
    "dlrover_tpu_slice_mfu",
    "dlrover_tpu_slice_workers",
    "dlrover_tpu_worker_hbm_peak_mb",
    "dlrover_tpu_node_hbm_used_mb",
    "dlrover_tpu_steptrace_gating_rank",
    "dlrover_tpu_steptrace_gating_seconds",
    "dlrover_tpu_steptrace_cross_slice_wait_fraction",
    "dlrover_tpu_capacity_offers_open",
    "dlrover_tpu_autoscale_quarantined_classes",
)


class TsdbCollector:
    """Master-side sampler + flusher: every ``sample_interval_s`` it
    snapshots the allowlisted registry gauges and the goodput ledger
    into the store, and every ``flush_interval_s`` it persists the
    downsampled tiers through the sidecar. Injectable clock + manual
    ``sample_once``/``flush`` so tests drive it without threads."""

    def __init__(self, store: TimeSeriesStore, registry=None,
                 goodput_ledger=None, state_dir: str = "",
                 sample_interval_s: Optional[float] = None,
                 flush_interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        from dlrover_tpu.common.config import Context
        from dlrover_tpu.obs.metrics import get_registry

        ctx = Context.singleton()
        self._store = store
        self._registry = registry if registry is not None \
            else get_registry()
        self._goodput = goodput_ledger
        self._sample_interval_s = (
            sample_interval_s if sample_interval_s is not None
            else ctx.tsdb_sample_interval_s)
        self._flush_interval_s = (
            flush_interval_s if flush_interval_s is not None
            else ctx.tsdb_flush_interval_s)
        self._clock = clock
        self._sidecar = (TimeSeriesSidecar(state_dir)
                         if state_dir else None)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_flush = 0.0
        # fence gate (wired by JobMaster in shared-state-dir setups):
        # a callable answering True when a higher-generation master
        # owns the lineage — a superseded primary's collector must
        # stop overwriting the promoted master's sidecar history
        self.gate: Optional[Callable[[], bool]] = None

    def restore(self) -> int:
        """Reload persisted history (master restart / standby
        promotion); 0 without a state dir or prior file."""
        if self._sidecar is None:
            return 0
        return self._sidecar.load(self._store)

    def sample_once(self, ts: Optional[float] = None) -> int:
        """One sampling tick; returns the number of points ingested."""
        now = self._clock() if ts is None else float(ts)
        count = 0
        fed = set()
        for name, labels, value in self._registry.sample_values(
                COLLECTED_PREFIXES):
            fed.add((name, _labels_key(labels or None)))
            # every allowlisted family is physically non-negative; a
            # negative reading is a "no evidence yet" sentinel (e.g.
            # training_mfu = -1 before a FLOPs model arrives) that
            # would poison bucket mins/means as fake data
            if isinstance(value, (int, float)) and value < 0:
                continue
            if self._store.ingest(name, value, labels=labels or None,
                                  ts=now):
                count += 1
        if self._goodput is not None:
            try:
                snap = self._goodput.snapshot()
            except Exception:  # noqa: BLE001 — evidence, not liveness
                snap = {}
            if snap:
                # one feed per series: the master registry already
                # carries the ledger's fraction gauge + seconds counter
                # (obs/goodput.py registers them), so the manual ingest
                # only covers bare-ledger wirings whose registry did
                # not emit the series this tick — double-landing the
                # same tick would double bucket counts/sums and fill
                # the raw ring at 2x
                if ("dlrover_tpu_goodput_fraction", ()) not in fed \
                        and self._store.ingest(
                            "dlrover_tpu_goodput_fraction",
                            float(snap.get("goodput_fraction", 0.0)),
                            ts=now):
                    count += 1
                for bucket, seconds in (snap.get("buckets")
                                        or {}).items():
                    key = ("dlrover_tpu_goodput_seconds_total",
                           (("bucket", str(bucket)),))
                    if key not in fed and self._store.ingest(
                            key[0], float(seconds),
                            {"bucket": str(bucket)}, ts=now):
                        count += 1
        return count

    def flush(self) -> bool:
        """Persist the downsampled tiers now (collector cadence, master
        stop, and tests). A fenced master (see ``gate``) keeps its
        cadence but never touches the file again."""
        if self._sidecar is None:
            return False
        # cadence marker only: stop() joins the loop before its final
        # flush, and a raced float write merely shifts one interval
        self._last_flush = self._clock()  # graftlint: disable=GL701
        if self.gate is not None and self.gate():
            return False
        return self._sidecar.save(self._store, gate=self.gate)

    def start(self) -> None:
        if self._sample_interval_s <= 0 or self._thread is not None:
            return
        self._stopped.clear()
        thread = threading.Thread(target=self._loop, daemon=True,
                                  name="tsdb-collector")
        self._thread = thread
        thread.start()

    def stop(self) -> None:
        self._stopped.set()
        thread, self._thread = self._thread, None
        # join before the final flush: a loop iteration mid-flush must
        # finish first (the tmp names are unique, but two concurrent
        # saves could still rename out of order — older over newer)
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)
        self.flush()

    def _loop(self) -> None:
        failing = False
        while not self._stopped.wait(self._sample_interval_s):
            try:
                self.sample_once()
                if (self._flush_interval_s > 0
                        and self._clock() - self._last_flush
                        >= self._flush_interval_s):
                    self.flush()
                failing = False
            except Exception:  # noqa: BLE001 — sampling must survive
                # a bad tick; the store is observability, not the job.
                # Logged once per failure STREAK: a persistently
                # unwritable state dir means silent history loss the
                # operator must hear about, but not once per second.
                if not failing:
                    logger.exception("tsdb collector tick failed "
                                     "(suppressing repeats until one "
                                     "succeeds)")
                failing = True

    def flight_snapshot(self, window_s: float = 900.0,
                        resolution_s: float = 0.0,
                        names: Sequence[str] = ()) -> Dict[str, Any]:
        """A compact dict of recent history for the master's flight
        dump (``tools/top.py --flight`` renders sparklines from it
        without a live master)."""
        wanted = list(names) or list(DASHBOARD_SERIES)
        series = []
        for name in wanted:
            series.extend(self._store.query(
                name, window_s=window_s, resolution_s=resolution_s))
        return {"version": TSDB_VERSION, "window_s": window_s,
                "series": series, "stats": self._store.stats()}
