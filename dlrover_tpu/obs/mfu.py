"""Model-FLOPs / MFU accounting: one formula, every consumer.

``bench.py`` proved the conservative accounting (6·params matmul credit
plus the causal-discounted attention term); this module makes that the
framework's single source so the worker's step reports, the master's
gauges and the benches can never drift apart. The analytic model is
cross-checkable against what XLA actually compiled via
:func:`cost_analysis_flops` (``jax.jit(...).lower(...).compile()
.cost_analysis()``) — callers pass the compiled object in, so this
module stays import-light (no jax dependency).

stdlib-only by design (imported by the master and tools without jax).
"""

from __future__ import annotations

from typing import Optional

# bf16 peak FLOP/s per chip by device kind (public specs). Longest
# matching prefix wins ("TPU v5 lite" must not resolve as "TPU v5").
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,          # v5p
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}

# fallbacks when the device kind is unknown: a TPU backend defaults to
# the v5p figure; anything else (CPU dev boxes) to a nominal 1 TFLOP/s
# so MFU stays a finite, obviously-synthetic number instead of inf/0
_DEFAULT_TPU_PEAK = 459e12
_DEFAULT_OTHER_PEAK = 1e12


def peak_flops_per_chip(device_kind: str = "",
                        backend: str = "") -> float:
    """Peak bf16 FLOP/s for one chip of ``device_kind`` (longest-prefix
    table match), falling back by ``backend`` name."""
    best = 0.0
    best_len = -1
    for name, flops in PEAK_FLOPS_BY_KIND.items():
        if device_kind.startswith(name) and len(name) > best_len:
            best, best_len = flops, len(name)
    if best:
        return best
    return _DEFAULT_TPU_PEAK if backend == "tpu" else _DEFAULT_OTHER_PEAK


def flops_per_token(param_count: float, num_layers: int = 0,
                    hidden_size: int = 0, seq_len: int = 0,
                    uncounted_embed_params: float = 0.0) -> float:
    """Model FLOPs per trained token (fwd+bwd), conservatively.

    ``6·params`` credits the matmul FLOPs of forward (2·params) plus
    backward (4·params). ``uncounted_embed_params`` subtracts parameters
    that do no matmul (a gather-lookup embedding table with untied
    output head). The attention term is QK^T + PV = 4·h·s FLOPs/token
    forward, ×3 for fwd+bwd, ÷2 causal — matching what a
    block-skipping flash kernel actually computes. With
    ``num_layers``/``hidden_size``/``seq_len`` unknown (0), the formula
    degrades to the bare 6·params floor.
    """
    counted = max(0.0, float(param_count) - float(uncounted_embed_params))
    attention = 6.0 * num_layers * hidden_size * seq_len
    return 6.0 * counted + attention


def achieved_mfu(tokens_per_second: float, flops_per_token_: float,
                 peak_flops_total: float) -> float:
    """Achieved / peak model-FLOPs utilization; -1.0 when the FLOPs
    model or the peak is unknown (callers must not mistake "no
    evidence" for "0 % utilized")."""
    if flops_per_token_ <= 0.0 or peak_flops_total <= 0.0:
        return -1.0
    if tokens_per_second < 0.0:
        return -1.0
    return tokens_per_second * flops_per_token_ / peak_flops_total


def cost_analysis_flops(compiled) -> float:
    """FLOPs per execution of an XLA-compiled program, from
    ``compiled.cost_analysis()`` — the cross-check for the analytic
    model. Returns 0.0 whenever the backend/object cannot answer (cost
    analysis is advisory; it must never break reporting)."""
    if compiled is None:
        return 0.0
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend support varies
        return 0.0
    # jax has returned both a dict and a one-element list of dicts
    # across versions
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return 0.0
    try:
        return float(analysis.get("flops", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def cross_check(analytic_per_token: float, measured_per_execution: float,
                tokens_per_execution: float,
                tolerance_ratio: float = 2.0) -> Optional[float]:
    """Compare the analytic FLOPs/token against a cost-analysis
    measurement. Returns the measured FLOPs/token when it diverges from
    the analytic model by more than ``tolerance_ratio`` in either
    direction (the measurement should then be adopted), else None (the
    analytic model stands). A 0/unknown measurement always returns
    None."""
    if measured_per_execution <= 0.0 or tokens_per_execution <= 0.0:
        return None
    measured_per_token = measured_per_execution / tokens_per_execution
    if analytic_per_token <= 0.0:
        return measured_per_token
    ratio = measured_per_token / analytic_per_token
    if ratio > tolerance_ratio or ratio < 1.0 / tolerance_ratio:
        return measured_per_token
    return None
