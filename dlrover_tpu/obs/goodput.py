"""Goodput ledger: classify the job's wall-clock, per rank and job-wide.

BENCH_r05 says restore-at-scale is 105.5 s and 7B MFU is 0.59 — but
nothing rolls the span stream up into "of the last hour, X% was
productive steps, Y% recompile, Z% restore". The ledger is that
accounting layer: every rank-second of the job lands in exactly one
bucket —

- ``productive``       — steps making forward progress (step reports,
                         net of their data-wait fraction),
- ``data_wait``        — step time starving on the input pipeline,
- ``compile``          — re-lower/re-jit after an elastic resize
                         (``recompile`` spans, phase=relower; the AOT
                         phase overlaps the restore read and is counted
                         under ``restore``),
- ``rendezvous``       — agents joining/re-forming a world
                         (``rendezvous``/``reconnect`` spans),
- ``restore``          — the ``restore_or_init`` path (checkpoint read +
                         device put + overlapped compile),
- ``checkpoint_stall`` — blocking commit waits and emergency saves
                         (``checkpoint_wait``/``emergency_checkpoint``;
                         the async interval save's dispatch rides inside
                         step time and is deliberately NOT re-counted),
- ``drain``            — preemption drains, notice → departure,
- ``hang``             — time a rank made no progress before a
                         hang-classified exit (estimated from its last
                         activity),
- ``idle``             — the residual nothing above accounts for
                         (derived at query time, never accrued).

Wall-clock is accounted in RANK-seconds: job-wide buckets are sums over
ranks, the denominator is the sum of per-rank lifetimes, and
``goodput_fraction = productive / elapsed``. Incarnations segment the
accounting at every world re-formation so a postmortem can say "the
drain at round 3 cost 41 s of badput" (``tools/goodput.py``).

Feeding (master side, wired by JobMaster/MasterServicer):

- ``observe_span`` from the telemetry ingest path (rank known from the
  TelemetryReport; span-id dedup absorbs standalone double delivery),
- ``observe_step_report`` from GlobalStepReport,
- ``mark_draining``/``complete_drain``/``observe_hang`` from the drain
  and failure handlers,
- ``observe_world`` from the comm-world path (opens incarnations).

stdlib-only by design; the clock is injectable so tests run on a fake
clock. Lock discipline: all shared state under ``self._lock``; registry
operations happen OUTSIDE the lock (sinks must never run under it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

PRODUCTIVE = "productive"
BADPUT_BUCKETS = ("data_wait", "compile", "rendezvous", "restore",
                  "checkpoint_stall", "drain", "hang", "idle")
BUCKETS = (PRODUCTIVE,) + BADPUT_BUCKETS

# span name → bucket. Nested/duplicate spans are deliberately absent:
# `rendezvous_join`/`rendezvous_round` live inside the agent's
# `rendezvous` trace, `checkpoint_restore` inside `restore_or_init`,
# `checkpoint_save` inside the reported step time, `master_restore` on
# the master while workers keep training.
_SPAN_BUCKETS = {
    "recompile": "compile",
    "rendezvous": "rendezvous",
    "reconnect": "rendezvous",
    "restore_or_init": "restore",
    "checkpoint_wait": "checkpoint_stall",
    "emergency_checkpoint": "checkpoint_stall",
    "drain": "drain",
}

_SEEN_SPAN_CAP = 4096      # span-id dedup ring
_WINDOW_CAP = 8192         # accrual records retained for windowed views
_INCARNATION_CAP = 64      # incarnation segments retained
_JOB_RANK = -1             # accruals not attributable to one rank


def classify_span(name: str, attrs: Optional[Dict[str, Any]] = None
                  ) -> str:
    """Bucket for a finished span, "" when the span is not ledger
    evidence (nested, master-side, or steady-state)."""
    bucket = _SPAN_BUCKETS.get(name, "")
    if bucket == "compile" and (attrs or {}).get("phase") == "aot":
        # the AOT compile overlaps the checkpoint read inside
        # restore_or_init (the loop pays max(read, compile)); counting
        # both would invent wall-clock
        return ""
    return bucket


class GoodputLedger:
    def __init__(self, registry=None,
                 now_fn: Callable[[], float] = time.time):
        from dlrover_tpu.obs.metrics import get_registry

        self._now = now_fn
        self._lock = threading.Lock()
        # rank -> {bucket: seconds} cumulative (idle excluded: derived)
        self._buckets: Dict[int, Dict[str, float]] = {}
        # rank lifetime: first_seen/last_activity/gone timestamps
        self._first_seen: Dict[int, float] = {}
        # graftlint: ephemeral(export is timestamp-free by design)
        self._last_activity: Dict[int, float] = {}
        self._gone: Dict[int, float] = {}
        self._state: Dict[int, str] = {}            # current activity
        # rank -> (notice_ts, rank bucket-total at notice): drain
        # accrues the notice→departure RESIDUAL, so accruals landing
        # inside the interval (the emergency checkpoint span, final
        # steps) are not double-counted
        self._draining_since: Dict[int, Tuple[float, float]] = {}
        self._last_step: Dict[int, int] = {}
        self._last_report_ts: Dict[int, float] = {}
        # graftlint: ephemeral(re-learned from the next step reports)
        self._mfu: Dict[int, float] = {}
        # multi-slice hierarchical DP: rank → slice (rendezvous slice
        # registry), per-rank degraded-step tallies (steps taken with
        # the gradient mean renormalized while a peer slice was
        # absent), and the slice label each rank's state gauge was
        # published under (removal must match the labels it was set
        # with, even across a slice-map update)
        self._slice_map: Dict[int, int] = {}
        self._degraded_steps: Dict[int, int] = {}
        # graftlint: ephemeral(gauge label memory; republished)
        self._state_slice: Dict[int, str] = {}
        # graftlint: ephemeral(span dedup; dead spans cannot recur)
        self._seen_span_ids: deque = deque(maxlen=_SEEN_SPAN_CAP)
        # graftlint: ephemeral(mirror of _seen_span_ids)
        self._seen_set: set = set()
        # online parallelism re-plans: the replan_plan/replan_migrate/
        # replan_rebuild sub-phase spans (nested inside the restore/
        # compile evidence — recorded here for the per-resize summary,
        # NOT accrued again as wall-clock)
        # graftlint: ephemeral(timestamped; excluded from export)
        self._replans: deque = deque(maxlen=64)
        # (ts, rank, bucket, seconds) for windowed summaries
        # graftlint: ephemeral(window samples; outage reads as idle)
        self._window: deque = deque(maxlen=_WINDOW_CAP)
        self._job_start = self._now()
        self._incarnations: deque = deque(maxlen=_INCARNATION_CAP)
        self._round = -1
        self._pending_reason = "job_start"
        with self._lock:
            self._open_incarnation(self._round, 0, self._pending_reason,
                                   self._job_start)
        registry = registry or get_registry()
        self._seconds_total = registry.counter(
            "dlrover_tpu_goodput_seconds_total",
            "Cumulative job wall-clock (rank-seconds) attributed to "
            "each goodput/badput bucket (idle is derived, see "
            "dlrover_tpu_goodput_fraction)", labelnames=("bucket",))
        self._events_total = registry.counter(
            "dlrover_tpu_elasticity_events_total",
            "World re-formations by trigger", labelnames=("kind",))
        self._state_gauge = registry.gauge(
            "dlrover_tpu_worker_goodput_state",
            "1 for the rank's current activity state",
            labelnames=("node", "slice", "state"))
        registry.gauge(
            "dlrover_tpu_goodput_fraction",
            "Cumulative productive fraction of the job's rank-seconds",
        ).set_function(self.goodput_fraction)

    # -- internal accrual (compute under lock, meter outside) --------------
    def _accrue_locked(self, rank: int, bucket: str, seconds: float,
                       ts: float) -> float:
        """Returns the seconds actually accrued (callers meter outside
        the lock)."""
        if seconds <= 0.0 or bucket not in BUCKETS or bucket == "idle":
            return 0.0
        table = self._buckets.setdefault(rank, {})
        table[bucket] = table.get(bucket, 0.0) + seconds
        self._window.append((ts, rank, bucket, seconds))
        inc = self._incarnations[-1]
        key = PRODUCTIVE if bucket == PRODUCTIVE else "badput"
        inc[key] = inc.get(key, 0.0) + seconds
        if bucket != PRODUCTIVE:
            per = inc.setdefault("badput_buckets", {})
            per[bucket] = per.get(bucket, 0.0) + seconds
        return seconds

    def _touch_locked(self, rank: int, ts: float) -> None:
        if rank == _JOB_RANK:
            return
        self._first_seen.setdefault(rank, ts)
        if ts > self._last_activity.get(rank, 0.0):
            self._last_activity[rank] = ts
        self._gone.pop(rank, None)

    def _open_incarnation(self, round_: int, world: int, reason: str,
                          ts: float) -> None:
        """(lock held)"""
        self._incarnations.append({
            "round": round_, "world": world, "reason": reason,
            "started_ts": ts, PRODUCTIVE: 0.0, "badput": 0.0,
            "badput_buckets": {},
        })

    def _set_state(self, rank: int, state: str
                   ) -> Optional[Tuple[int, str, str]]:
        """Under lock; returns (rank, old, new) when it changed so the
        caller updates the gauge outside the lock."""
        old = self._state.get(rank, "")
        if old == state:
            return None
        self._state[rank] = state
        return rank, old, state

    def _publish_state(self, change: Optional[Tuple[int, str, str]]
                       ) -> None:
        if change is None:
            return
        rank, old, new = change
        with self._lock:
            old_slice = self._state_slice.get(rank)
            new_slice = str(self._slice_map.get(rank, -1))
            if new:
                self._state_slice[rank] = new_slice
            else:
                self._state_slice.pop(rank, None)
        if old and old_slice is not None:
            self._state_gauge.remove(node=str(rank), slice=old_slice,
                                     state=old)
        if new:
            self._state_gauge.labels(node=str(rank), slice=new_slice,
                                     state=new).set(1)

    # -- slice membership (multi-slice hierarchical DP) --------------------
    def set_slice_map(self, slice_map: Dict[int, int]) -> None:
        with self._lock:
            self._slice_map = dict(slice_map)

    def observe_degraded_steps(self, rank: int, count: int) -> None:
        """``count`` degraded steps reported by ``rank``'s slice: the
        gradient mean was renormalized over present slices while a peer
        slice was absent. Tallied per rank for the snapshot/tools view
        (the labeled counter series is the servicer's)."""
        if count <= 0:
            return
        with self._lock:
            self._degraded_steps[rank] = (
                self._degraded_steps.get(rank, 0) + int(count))

    # -- evidence feeds ----------------------------------------------------
    def observe_span(self, record: Dict[str, Any],
                     rank: int = _JOB_RANK) -> bool:
        """One finished span dict (``Span.to_dict`` shape). Returns
        whether it was newly accounted (span-id re-deliveries — local
        sink + telemetry relay in a standalone process — are dropped)."""
        if not isinstance(record, dict):
            return False
        name = str(record.get("name", ""))
        bucket = classify_span(name, record.get("attrs"))
        span_id = record.get("span_id")
        try:
            duration = float(record.get("duration_s", 0.0))
        except (TypeError, ValueError):
            return False
        ts = float(record.get("ts", 0.0) or 0.0) or self._now()
        with self._lock:
            if span_id:
                if span_id in self._seen_set:
                    return False
                if len(self._seen_span_ids) == self._seen_span_ids.maxlen:
                    self._seen_set.discard(self._seen_span_ids[0])
                self._seen_span_ids.append(span_id)
                self._seen_set.add(span_id)
            if name.startswith("replan_") and duration >= 0.0:
                # the re-plan sub-phase decomposition (plan → migrate →
                # rebuild): per-resize evidence for the snapshot/tools
                # view. These spans nest INSIDE the restore/compile
                # evidence — recording them here never re-accrues their
                # wall-clock.
                attrs = record.get("attrs") or {}
                self._replans.append({
                    "phase": name[len("replan_"):],
                    "rank": rank,
                    "seconds": round(duration, 3),
                    "ts": ts,
                    "generation": attrs.get("generation", 0),
                    "detail": {k: v for k, v in attrs.items()
                               if k in ("source", "bytes", "resharded",
                                        "applied", "mesh")},
                })
            if not bucket or duration <= 0.0:
                return False
            self._touch_locked(rank, ts + duration)
            accrued = self._accrue_locked(rank, bucket, duration,
                                          ts + duration)
        if accrued > 0.0:
            self._seconds_total.labels(bucket=bucket).inc(accrued)
        return True

    def observe_step_report(self, rank: int, step: int,
                            step_time_s: float = 0.0,
                            data_wait_fraction: float = -1.0,
                            mfu: float = -1.0,
                            ts: Optional[float] = None) -> None:
        """Productive/data-wait accrual from one GlobalStepReport: the
        delta of steps since the rank's last report, at its reported
        mean step time, split by its data-wait fraction. A report with
        no timing evidence (step_time_s == 0) accrues nothing — the
        un-attributed time lands in ``idle``, honestly."""
        now = ts if ts is not None else self._now()
        metered: List[Tuple[str, float]] = []
        with self._lock:
            self._touch_locked(rank, now)
            change = self._set_state(rank, "steady")
            last_step = self._last_step.get(rank)
            last_ts = self._last_report_ts.get(rank)
            self._last_step[rank] = int(step)
            self._last_report_ts[rank] = now
            if mfu >= 0.0:
                self._mfu[rank] = mfu
            delta = (int(step) - last_step) if last_step is not None \
                else 0
            # accrual needs BOTH a prior step and a prior timestamp:
            # after a master restore last_ts restarts empty, so the
            # first report only re-anchors the cadence — its delta
            # spans the outage and must not become productive time
            if delta > 0 and step_time_s > 0.0 and last_ts is not None \
                    and now > last_ts:
                # never attribute more than the wall since the
                # previous report
                stepped = min(step_time_s * delta, now - last_ts)
                wait = min(1.0, max(0.0, data_wait_fraction))
                metered.append((PRODUCTIVE, self._accrue_locked(
                    rank, PRODUCTIVE, stepped * (1.0 - wait), now)))
                metered.append(("data_wait", self._accrue_locked(
                    rank, "data_wait", stepped * wait, now)))
        self._publish_state(change)
        for bucket, accrued in metered:
            if accrued > 0.0:
                self._seconds_total.labels(bucket=bucket).inc(accrued)

    def _rank_total_locked(self, rank: int) -> float:
        """(lock held)"""
        return sum(self._buckets.get(rank, {}).values())

    def mark_draining(self, rank: int, deadline: float = 0.0) -> None:
        now = self._now()
        with self._lock:
            self._touch_locked(rank, now)
            self._draining_since.setdefault(
                rank, (now, self._rank_total_locked(rank)))
            change = self._set_state(rank, "draining")
            self._pending_reason = "drain"
        self._publish_state(change)

    def complete_drain(self, rank: int) -> None:
        """The rank departed after its notice: the notice → departure
        interval is drain badput — net of whatever the interval already
        attributed elsewhere (the emergency-checkpoint span, final
        steps), so the same rank-second is never booked twice — and the
        rank's lifetime ends now."""
        now = self._now()
        with self._lock:
            marked = self._draining_since.pop(rank, None)
            accrued = 0.0
            if marked is not None:
                since, baseline = marked
                attributed_inside = max(
                    0.0, self._rank_total_locked(rank) - baseline)
                accrued = self._accrue_locked(
                    rank, "drain",
                    max(0.0, (now - since) - attributed_inside), now)
            change = self._set_state(rank, "")
            self._gone[rank] = now
            self._pending_reason = "drain"
        self._publish_state(change)
        if accrued > 0.0:
            self._seconds_total.labels(bucket="drain").inc(accrued)

    def observe_hang(self, rank: int,
                     hang_bound_s: float = 0.0) -> None:
        """A hang-classified worker exit: the time since the rank's last
        observed activity (bounded by the watchdog window when known)
        was a hang, not idle."""
        now = self._now()
        with self._lock:
            last = self._last_activity.get(rank, now)
            hang_s = max(0.0, now - last)
            if hang_bound_s > 0.0:
                hang_s = min(hang_s, hang_bound_s)
            self._touch_locked(rank, now)
            accrued = self._accrue_locked(rank, "hang", hang_s, now)
            self._pending_reason = "hang_restart"
        if accrued > 0.0:
            self._seconds_total.labels(bucket="hang").inc(accrued)

    def note_elasticity_event(self, kind: str) -> None:
        """Name the trigger the NEXT world re-formation is attributed to
        (drain / worker_lost / hang_restart / autoscale / scale).

        ``replan`` is the MECHANISM every world change rides through,
        not a root cause: it only fills an empty slot, so an autoscale
        claim (or a drain notice) that triggered the re-plan keeps the
        attribution instead of being clobbered by its own side effect."""
        with self._lock:
            if kind == "replan" and self._pending_reason:
                return
            self._pending_reason = kind

    def observe_world(self, round_: int, world_size: int) -> None:
        """A cut world observed (comm-world path): a new round opens a
        new incarnation attributed to the pending trigger."""
        now = self._now()
        with self._lock:
            if round_ <= self._round:
                return
            first = self._round < 0 and len(self._incarnations) == 1 \
                and self._incarnations[-1]["round"] == -1
            self._round = round_
            reason = self._pending_reason or "scale"
            self._pending_reason = ""
            if first:
                # the job's first world is not an elasticity event:
                # adopt the bootstrap segment instead of closing it
                self._incarnations[-1]["round"] = round_
                self._incarnations[-1]["world"] = world_size
                return
            self._open_incarnation(round_, world_size, reason, now)
        self._events_total.labels(kind=reason).inc()
        try:
            from dlrover_tpu.obs.flight_recorder import get_flight_recorder

            get_flight_recorder().record_event(
                "elasticity_event", round=round_, world=world_size,
                reason=reason)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass

    def evict(self, live) -> None:
        """Membership hook: ranks no longer alive stop accruing lifetime
        (their cumulative buckets persist — badput history outlives the
        rank)."""
        live_set = set(live)
        now = self._now()
        changes = []
        with self._lock:
            for rank in list(self._first_seen):
                if rank in live_set or rank in self._gone:
                    continue
                self._gone[rank] = now
                self._draining_since.pop(rank, None)
                changes.append(self._set_state(rank, ""))
        for change in changes:
            self._publish_state(change)

    # -- queries -----------------------------------------------------------
    def _rank_elapsed_locked(self, rank: int, now: float) -> float:
        end = self._gone.get(rank, now)
        return max(0.0, end - self._first_seen.get(rank, now))

    def goodput_fraction(self) -> float:
        with self._lock:
            now = self._now()
            elapsed = sum(self._rank_elapsed_locked(r, now)
                          for r in self._first_seen)
            productive = sum(t.get(PRODUCTIVE, 0.0)
                             for t in self._buckets.values())
        return productive / elapsed if elapsed > 0 else 0.0

    def snapshot(self, window_s: float = 0.0) -> Dict[str, Any]:
        """The full ledger as one JSON-safe dict: job-wide buckets
        (idle derived as the residual), per-rank rows, incarnation
        segments, and optionally a windowed summary."""
        with self._lock:
            now = self._now()
            per_rank: Dict[str, Any] = {}
            job: Dict[str, float] = {b: 0.0 for b in BUCKETS}
            total_elapsed = 0.0
            for rank in sorted(self._first_seen):
                elapsed = self._rank_elapsed_locked(rank, now)
                table = dict(self._buckets.get(rank, {}))
                known = sum(table.values())
                table["idle"] = max(0.0, elapsed - known)
                per_rank[str(rank)] = {
                    "elapsed_s": round(elapsed, 3),
                    "state": self._state.get(rank, ""),
                    "gone": rank in self._gone,
                    "mfu": round(self._mfu.get(rank, -1.0), 4),
                    "slice": self._slice_map.get(rank, -1),
                    "degraded_steps": self._degraded_steps.get(rank, 0),
                    "buckets": {b: round(s, 3)
                                for b, s in table.items() if s > 0.0},
                }
                total_elapsed += elapsed
                for bucket, seconds in table.items():
                    job[bucket] = job.get(bucket, 0.0) + seconds
            # accruals with no rank (job-scope spans) count job-wide
            for bucket, seconds in self._buckets.get(_JOB_RANK,
                                                     {}).items():
                job[bucket] = job.get(bucket, 0.0) + seconds
                total_elapsed += seconds
            incarnations = [dict(inc,
                                 badput_buckets=dict(
                                     inc.get("badput_buckets", {})))
                            for inc in self._incarnations]
            snap: Dict[str, Any] = {
                "version": 1,
                "job_start_ts": self._job_start,
                "now": now,
                "elapsed_rank_seconds": round(total_elapsed, 3),
                "buckets": {b: round(s, 3) for b, s in job.items()},
                "goodput_fraction": round(
                    job[PRODUCTIVE] / total_elapsed, 4)
                if total_elapsed > 0 else 0.0,
                "per_rank": per_rank,
                "incarnations": incarnations,
                "degraded_steps_total": sum(
                    self._degraded_steps.values()),
                "replans": self._replan_summary_locked(),
            }
        if window_s > 0.0:
            snap["window"] = self.window_summary(window_s)
        return snap

    def _replan_summary_locked(self) -> List[Dict[str, Any]]:
        """(lock held) One row per resize: the replan sub-phase spans
        grouped by (rank, plan generation) — the per-event "what did
        this re-plan cost vs a checkpoint round-trip" evidence
        (tools/goodput.py, tools/diagnose.py)."""
        grouped: Dict[Tuple[int, Any], Dict[str, Any]] = {}
        for record in self._replans:
            key = (record["rank"], record["generation"])
            row = grouped.setdefault(key, {
                "rank": record["rank"],
                "generation": record["generation"],
                "ts": record["ts"], "phases": {}, })
            phases = row["phases"]
            phases[record["phase"]] = round(
                phases.get(record["phase"], 0.0) + record["seconds"], 3)
            row["ts"] = max(row["ts"], record["ts"])
            for k, v in record["detail"].items():
                row.setdefault(k, v)
        return sorted(grouped.values(), key=lambda r: r["ts"])

    def window_summary(self, window_s: float) -> Dict[str, Any]:
        """Buckets accrued over the trailing window, with the window's
        elapsed rank-seconds as denominator and the dominant badput
        bucket named (the alert rule's evidence)."""
        with self._lock:
            now = self._now()
            start = now - window_s
            # a full accrual ring may no longer reach back the whole
            # window: shrink the effective window to what the ring
            # actually covers, or the evicted accruals would read as
            # idle and a busy large job would raise a FALSE goodput
            # alert (the denominator must match the accrual evidence)
            truncated = False
            if len(self._window) == self._window.maxlen:
                oldest_ts = self._window[0][0]
                if oldest_ts > start:
                    start = oldest_ts
                    truncated = True
            buckets: Dict[str, float] = {}
            for ts, _, bucket, seconds in self._window:
                if ts >= start:
                    # an accrual records the END of its interval: clip
                    # the part that happened before the window opened
                    # (a long restore ending just inside the window
                    # must not dominate it wholesale)
                    buckets[bucket] = buckets.get(bucket, 0.0) \
                        + min(seconds, ts - start)
            elapsed = 0.0
            for rank in self._first_seen:
                end = self._gone.get(rank, now)
                begin = max(self._first_seen[rank], start)
                elapsed += max(0.0, end - begin)
        known = sum(buckets.values())
        buckets["idle"] = max(0.0, elapsed - known)
        productive = buckets.get(PRODUCTIVE, 0.0)
        dominant = ""
        worst = 0.0
        for bucket, seconds in buckets.items():
            if bucket != PRODUCTIVE and seconds > worst:
                dominant, worst = bucket, seconds
        summary = {
            "window_s": window_s,
            "elapsed_rank_seconds": round(elapsed, 3),
            "buckets": {b: round(s, 3) for b, s in buckets.items()
                        if s > 0.0},
            "goodput_fraction": round(productive / elapsed, 4)
            if elapsed > 0 else -1.0,
            "dominant_badput": dominant,
            "dominant_badput_s": round(worst, 3),
        }
        if truncated:
            summary["effective_window_s"] = round(now - start, 3)
            summary["truncated"] = True
        return summary

    def record_flight_snapshot(self, reason: str = "") -> None:
        """Drop the current snapshot into the flight recorder so a
        postmortem dump carries the ledger (``tools/goodput.py
        --flight``)."""
        try:
            from dlrover_tpu.obs.flight_recorder import get_flight_recorder

            get_flight_recorder().record_event(
                "goodput", reason=reason, snapshot=self.snapshot())
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        # deliberately timestamp-free: the master's save_if_changed
        # dedups snapshots by content, so a steady-state export must be
        # byte-identical to the previous one
        with self._lock:
            return {
                "job_start_ts": self._job_start,
                "round": self._round,
                "buckets": {str(r): dict(t)
                            for r, t in self._buckets.items()},
                "first_seen": {str(r): t
                               for r, t in self._first_seen.items()},
                "gone": {str(r): t for r, t in self._gone.items()},
                "last_step": {str(r): s
                              for r, s in self._last_step.items()},
                "incarnations": [dict(inc, badput_buckets=dict(
                    inc.get("badput_buckets", {})))
                    for inc in self._incarnations],
                "slices": {str(r): s
                           for r, s in self._slice_map.items()},
                "degraded_steps": {
                    str(r): n
                    for r, n in self._degraded_steps.items()},
            }

    def restore_state(self, state: dict) -> None:
        """Rehydrate cumulative accounting after a master restart. The
        outage gap accrues as idle (elapsed keeps running from
        first_seen); report cadence restarts fresh so the first
        post-restart report never double-attributes the gap. The
        Prometheus counters deliberately do NOT replay the restored
        totals: they are process-lifetime series (a restart reset is
        standard counter semantics, and an in-process master restart
        shares the registry — replaying would double-count); the
        snapshot/RPC view carries the job-cumulative numbers."""
        with self._lock:
            self._job_start = float(state.get("job_start_ts",
                                              self._job_start))
            self._round = int(state.get("round", -1))
            self._buckets.clear()
            for rank, table in (state.get("buckets") or {}).items():
                if not isinstance(table, dict):
                    continue
                clean = {b: float(s) for b, s in table.items()
                         if b in BUCKETS and b != "idle"}
                self._buckets[int(rank)] = clean
            self._first_seen = {int(r): float(t) for r, t in
                                (state.get("first_seen") or {}).items()}
            self._gone = {int(r): float(t) for r, t in
                          (state.get("gone") or {}).items()}
            self._last_step = {int(r): int(s) for r, s in
                               (state.get("last_step") or {}).items()}
            self._slice_map = {int(r): int(s) for r, s in
                               (state.get("slices") or {}).items()}
            self._degraded_steps = {
                int(r): int(n) for r, n in
                (state.get("degraded_steps") or {}).items()}
            # report timestamps deliberately restart: the next report's
            # delta spans the outage and must clamp to zero wall
            self._last_report_ts.clear()
            self._draining_since.clear()
            self._state.clear()
            self._incarnations.clear()
            for inc in state.get("incarnations") or []:
                if isinstance(inc, dict):
                    self._incarnations.append(dict(inc))
            if not self._incarnations:
                self._open_incarnation(self._round, 0, "job_start",
                                       self._job_start)
            self._pending_reason = "master_failover"


# --------------------------------------------------------------------------
# rendering (tools/goodput.py, tools/diagnose.py, tools/obs_dump.py)
# --------------------------------------------------------------------------


def _fmt_buckets(buckets: Dict[str, float], elapsed: float) -> List[str]:
    lines = []
    for bucket in BUCKETS:
        seconds = buckets.get(bucket, 0.0)
        if seconds <= 0.0:
            continue
        pct = 100.0 * seconds / elapsed if elapsed > 0 else 0.0
        lines.append(f"  {bucket:<16} {seconds:>10.1f}s  {pct:5.1f}%")
    return lines


def render_snapshot(snap: Dict[str, Any]) -> str:
    """Human-readable ledger report from a `GoodputLedger.snapshot()`
    dict (live RPC or flight dump)."""
    elapsed = float(snap.get("elapsed_rank_seconds", 0.0))
    buckets = snap.get("buckets", {})
    lines = [
        "goodput ledger: {:.1f} rank-seconds accounted, goodput "
        "{:.1%}".format(elapsed,
                        float(snap.get("goodput_fraction", 0.0))),
    ]
    lines += _fmt_buckets(buckets, elapsed)
    window = snap.get("window")
    if window:
        lines.append(
            "window ({:.0f}s): goodput {:.1%}, dominant badput: "
            "{} ({:.1f}s)".format(
                float(window.get("window_s", 0.0)),
                max(0.0, float(window.get("goodput_fraction", 0.0))),
                window.get("dominant_badput") or "-",
                float(window.get("dominant_badput_s", 0.0))))
    per_rank = snap.get("per_rank", {})
    # per-slice rollup (multi-slice hierarchical DP): grouped by
    # failure domain, with the degraded-step tally front and center
    slice_rows: Dict[Any, List[Dict[str, Any]]] = {}
    for row in per_rank.values():
        sid = row.get("slice", -1)
        if sid is not None and int(sid) >= 0:
            slice_rows.setdefault(int(sid), []).append(row)
    degraded_total = int(snap.get("degraded_steps_total", 0) or 0)
    if slice_rows:
        lines.append("per slice:")
        for sid in sorted(slice_rows):
            rows = slice_rows[sid]
            elapsed_s = sum(float(r.get("elapsed_s", 0.0))
                            for r in rows)
            productive = sum(
                float(r.get("buckets", {}).get(PRODUCTIVE, 0.0))
                for r in rows)
            degraded = sum(int(r.get("degraded_steps", 0))
                           for r in rows)
            fraction = productive / elapsed_s if elapsed_s > 0 else 0.0
            gone = all(r.get("gone") for r in rows)
            lines.append(
                f"  slice {sid:>3}  {len(rows)} rank(s)  "
                f"{elapsed_s:8.1f}s elapsed  goodput {fraction:6.1%}  "
                f"degraded_steps={degraded}"
                + ("  [gone]" if gone else ""))
    elif degraded_total:
        lines.append(f"degraded steps (renormalized gradient mean): "
                     f"{degraded_total}")
    if per_rank:
        lines.append("per rank:")
        for rank in sorted(per_rank, key=lambda r: int(r)):
            row = per_rank[rank]
            row_buckets = row.get("buckets", {})
            row_elapsed = float(row.get("elapsed_s", 0.0))
            productive = float(row_buckets.get(PRODUCTIVE, 0.0))
            fraction = productive / row_elapsed if row_elapsed > 0 \
                else 0.0
            top = sorted(((b, s) for b, s in row_buckets.items()
                          if b != PRODUCTIVE),
                         key=lambda kv: -kv[1])[:3]
            detail = " ".join(f"{b}={s:.1f}s" for b, s in top)
            mfu = float(row.get("mfu", -1.0))
            mfu_txt = f" mfu={mfu:.3f}" if mfu >= 0.0 else ""
            state = row.get("state") or ("gone" if row.get("gone")
                                         else "-")
            lines.append(
                f"  rank {rank:>4}  {row_elapsed:8.1f}s elapsed  "
                f"goodput {fraction:6.1%}  [{state}]{mfu_txt}  "
                f"{detail}".rstrip())
    replans = snap.get("replans", [])
    if replans:
        # per-resize pricing: the plan → migrate → rebuild legs of each
        # online re-plan (vs the checkpoint round-trip it replaced)
        lines.append("re-plans (plan / migrate / rebuild), per resize:")
        for row in replans:
            phases = row.get("phases", {})
            legs = " ".join(
                f"{phase}={phases[phase]:.2f}s"
                for phase in ("plan", "migrate", "rebuild")
                if phase in phases)
            detail = []
            if row.get("source"):
                detail.append(f"source={row['source']}")
            if row.get("bytes"):
                detail.append(
                    f"{float(row['bytes']) / (1 << 20):.1f}MiB moved")
            if row.get("resharded"):
                detail.append("resharded")
            total = sum(phases.values())
            lines.append(
                "  rank {rank} gen {gen}: {total:.2f}s total  {legs}"
                "{detail}".format(
                    rank=row.get("rank", "?"),
                    gen=row.get("generation", "?"),
                    total=total, legs=legs,
                    detail=("  [" + " ".join(detail) + "]")
                    if detail else "").rstrip())
    incarnations = snap.get("incarnations", [])
    if incarnations:
        lines.append("time lost to elasticity events, per incarnation:")
        for index, inc in enumerate(incarnations):
            per = inc.get("badput_buckets", {})
            top = sorted(per.items(), key=lambda kv: -kv[1])[:3]
            detail = " ".join(f"{b}={s:.1f}s" for b, s in top) or "-"
            lines.append(
                "  #{idx} round={round} world={world} "
                "trigger={reason}: badput {badput:.1f}s "
                "(productive {productive:.1f}s)  {detail}".format(
                    idx=index, round=inc.get("round", "?"),
                    world=inc.get("world", "?"),
                    reason=inc.get("reason", "?"),
                    badput=float(inc.get("badput", 0.0)),
                    productive=float(inc.get(PRODUCTIVE, 0.0)),
                    detail=detail).rstrip())
    return "\n".join(lines)


def snapshot_from_flight(payload: Dict[str, Any]
                         ) -> Optional[Dict[str, Any]]:
    """The newest `goodput` snapshot event of a flight dump, or a
    spans-only rebuild when the dump predates snapshot recording (the
    rebuild has no step reports, so productive time is absent and the
    residual reads as idle)."""
    newest = None
    for record in payload.get("events", []):
        if record.get("kind") == "event" and \
                record.get("name") == "goodput":
            snap = record.get("attrs", {}).get("snapshot")
            if isinstance(snap, dict):
                newest = snap
    if newest is not None:
        return newest
    # fallback: replay span records through a throwaway ledger
    spans = [r for r in payload.get("events", [])
             if r.get("kind") == "span"]
    if not spans:
        return None
    from dlrover_tpu.obs.metrics import MetricsRegistry

    ledger = GoodputLedger(registry=MetricsRegistry())
    for record in spans:
        ledger.observe_span(record)
    snap = ledger.snapshot()
    snap["rebuilt_from_spans"] = True
    return snap
