"""Static model analysis + axis sizing feeding strategy planning.

Capability parity: atorch Analyser (atorch/auto/analyser/analyser.py —
model size, dtypes, module inventory) and the graph-sharding planners that
SIZE parallel axes from the model and device topology
(auto/opt_lib/shard_planners/mip_tp_planner.py:30). TPU re-design: all
analysis is abstract (`jax.eval_shape`, nothing materialized) and the MIP
over NVLink topology becomes closed-form sizing over the homogeneous
device mesh — fsdp from HBM fit of the optimizer state, tensor from head
divisibility and residual HBM pressure, remat from the activation
footprint.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.auto.model_context import ModelContext

# Fraction of HBM the train state (params + optimizer) may claim; the rest
# is activations, XLA scratch, and fragmentation headroom.
STATE_HBM_FRACTION = 0.6
# Rough fwd+bwd live-activation bytes per token per layer, in units of
# hidden_size × activation bytes: residual stream + qkv + attention
# internals + mlp intermediates (SwiGLU ≈ 2.7×hidden) saved for backward.
ACTIVATION_FACTOR = 14.0


def _model_dims(context: ModelContext) -> Dict[str, int]:
    """Pull transformer dimensions from a dataclass model config when one
    exists (LlamaConfig / GPTConfig / MoE variants)."""
    cfg = context.model_config()
    if cfg is None:
        return {}
    get = lambda *names: next(
        (int(getattr(cfg, n)) for n in names if hasattr(cfg, n)), 0)
    return {
        "hidden_size": get("hidden_size", "n_embd"),
        "num_layers": get("num_layers", "n_layer"),
        "num_heads": get("num_heads", "n_head"),
        "num_kv_heads": get("num_kv_heads", "num_heads", "n_head"),
        "vocab_size": get("vocab_size"),
        "intermediate_size": get("intermediate_size"),
        "num_experts": get("num_experts"),
    }


def _train_state_bytes(context: ModelContext, abstract_params: Any,
                       param_count: int, param_bytes: int) -> int:
    """params + grads + the ACTUAL optimizer state, measured by
    eval_shape-ing `tx.init` on the abstract params (an adafactor user
    must not be sized as if they carried fp32 Adam moments — factored
    state is ~100x leaner). Falls back to the classic Adam-family upper
    bound (~20 B/param: fp32 master + 2 fp32 moments + grad + fp32
    accumulator) when no optimizer factory is available or its init
    cannot be traced abstractly."""
    try:
        tx = context.make_optimizer()
    except Exception:
        return param_count * 20
    try:
        import flax.linen as nn

        plain = nn.unbox(abstract_params)
        if isinstance(plain, dict) and "params" in plain:
            plain = plain["params"]
        abstract_opt = jax.eval_shape(tx.init, plain)
        opt_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(abstract_opt)
            if hasattr(leaf, "shape"))
    except Exception:
        return param_count * 20
    # params + one transient same-dtype grad (live during value_and_grad)
    # + the persistent fp32 grad accumulator build_trainer carries
    # (trainer/train_step.py micro_step) + the measured optimizer state
    return 2 * param_bytes + param_count * 4 + opt_bytes


def analyse(context: ModelContext, micro_batch: int = 1) -> Dict[str, Any]:
    sample = np.asarray(context.infer_sample_batch(micro_batch))

    def _init():
        return context.model.init(jax.random.PRNGKey(0),
                                  jnp.asarray(sample))

    abstract = jax.eval_shape(_init)
    leaves = jax.tree.leaves(abstract)
    param_count = sum(int(np.prod(leaf.shape)) for leaf in leaves)
    param_bytes = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize for leaf in leaves)
    dtypes = sorted({str(leaf.dtype) for leaf in leaves})
    train_state_bytes = _train_state_bytes(context, abstract, param_count,
                                           param_bytes)
    device = context.devices[0]
    try:
        hbm_bytes = int(os.environ.get("DLROVER_TPU_HBM_BYTES") or 0)
    except ValueError:
        hbm_bytes = 0
    if not hbm_bytes:
        stats = getattr(device, "memory_stats", lambda: None)()
        if stats:
            hbm_bytes = stats.get("bytes_limit", 0)
    dims = _model_dims(context)
    seq_len = int(sample.shape[-1]) if sample.ndim >= 2 else 0
    activation_bytes = 0
    if dims.get("hidden_size") and dims.get("num_layers") and seq_len:
        # bf16 activations (2 bytes) saved for backward, per microbatch
        activation_bytes = int(
            micro_batch * seq_len * dims["num_layers"]
            * dims["hidden_size"] * ACTIVATION_FACTOR * 2)
    return {
        "param_count": param_count,
        "param_bytes": param_bytes,
        "param_dtypes": dtypes,
        "train_state_bytes": train_state_bytes,
        "activation_bytes": activation_bytes,
        "seq_len": seq_len,
        "device_hbm_bytes": hbm_bytes,
        "n_devices": len(context.devices),
        # DCN granules (mirrors parallel/mesh.py's hybrid-mesh rule):
        # slices when reported, else processes — >1 means the data-axis
        # gradient reduce crosses the slow fabric
        "n_dcn_granules": _dcn_granules(context.devices),
        "fits_one_device": (
            hbm_bytes == 0
            or train_state_bytes < hbm_bytes * STATE_HBM_FRACTION),
        **dims,
    }


def _dcn_granules(devices) -> int:
    from dlrover_tpu.parallel.mesh import dcn_granules

    return dcn_granules(devices)[0]


def _divisors_of(n: int):
    return [d for d in range(1, n + 1) if n % d == 0]


def size_axes(info: Dict[str, Any]) -> Dict[str, Any]:
    """Closed-form axis sizing from the analysis (reference role:
    mip_tp_planner.py:30 sizes TP splits from graph + topology).

    Policy (homogeneous TPU mesh):
    1. fsdp: smallest divisor of n_devices whose shard of the train
       state fits STATE_HBM_FRACTION of one device's HBM. (Tensor
       parallelism cannot improve the STATE fit — weights shard over
       fsdp × tensor either way — so state sizing is fsdp-only.)
    2. remat: on when the per-microbatch activation footprint doesn't
       fit the HBM left after the state shard; rematerialization keeps
       roughly the residual stream (~15% of saved activations).
    3. tensor: only when activations still don't fit AFTER remat —
       sized to the smallest divisor of the remaining devices that
       divides BOTH num_heads and num_kv_heads (Megatron head-split
       constraint) and makes the width-sharded activations fit.
    4. sequence: the long-context escape hatch — when activations
       still don't fit after remat AND tensor (the sequence is so long
       that even a single layer's width-sharded activations blow the
       budget), shard the sequence dim over remaining devices (ring
       attention keeps the math exact).
    5. expert: for MoE configs (num_experts > 1), the largest divisor
       of the remaining devices that divides the expert count — expert
       weights dominate MoE state, and the expert axis shards them
       with one all-to-all per MoE layer instead of fsdp's per-matmul
       re-gathers.
    6. data: whatever devices remain.

    Returns {"fsdp", "tensor", "sequence", "expert", "data", "remat"};
    sizes are all 1 when the device HBM is unknown, EXCEPT expert,
    which depends only on the model config and device count.
    """
    n_devices = info["n_devices"]
    hbm = info["device_hbm_bytes"]

    def _expert_size(remaining: int) -> int:
        experts = info.get("num_experts", 0) or 0
        if experts <= 1 or remaining < 2:
            return 1
        return max((d for d in _divisors_of(remaining)
                    if d <= experts and experts % d == 0), default=1)

    if not hbm or n_devices < 1:
        expert = _expert_size(n_devices or 1)
        return {"fsdp": 1, "tensor": 1, "sequence": 1, "expert": expert,
                "data": max(1, (n_devices or 1) // expert),
                "remat": False}
    state_budget = hbm * STATE_HBM_FRACTION
    state = info["train_state_bytes"]

    fsdp = next((d for d in _divisors_of(n_devices)
                 if state / d <= state_budget), n_devices)

    free_after_state = max(hbm - state / fsdp, hbm * 0.1)
    act_budget = free_after_state * 0.8
    act = float(info.get("activation_bytes", 0))
    remat = bool(act and act > act_budget)
    # remat keeps ~the residual stream: 2/ACTIVATION_FACTOR of the saved
    # activations, recomputing the rest inside each layer
    act_eff = act * (2.0 / ACTIVATION_FACTOR) if remat else act

    tensor = 1
    heads = info.get("num_heads", 0)
    kv_heads = info.get("num_kv_heads", 0) or heads
    if act_eff > act_budget and heads:
        for d in _divisors_of(n_devices // fsdp):
            if d > 1 and heads % d == 0 and kv_heads % d == 0:
                tensor = d
                if act_eff / d <= act_budget:
                    break

    sequence = 1
    seq_len = info.get("seq_len", 0)
    if act_eff / tensor > act_budget and seq_len:
        for d in _divisors_of(n_devices // (fsdp * tensor)):
            if d > 1 and seq_len % d == 0:
                sequence = d
                if act_eff / (tensor * d) <= act_budget:
                    break

    expert = _expert_size(n_devices // (fsdp * tensor * sequence))
    data = n_devices // (fsdp * tensor * sequence * expert)
    return {"fsdp": fsdp, "tensor": tensor, "sequence": sequence,
            "expert": expert, "data": max(1, data), "remat": remat}
