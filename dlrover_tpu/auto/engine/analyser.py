"""Static model analysis feeding strategy pruning.

Capability parity: atorch Analyser (atorch/auto/analyser/analyser.py) —
model size, dtypes, module inventory — done abstractly with
`jax.eval_shape` so nothing is materialized.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.auto.model_context import ModelContext


def analyse(context: ModelContext, micro_batch: int = 1) -> Dict[str, Any]:
    sample = np.asarray(context.infer_sample_batch(micro_batch))

    def _init():
        return context.model.init(jax.random.PRNGKey(0),
                                  jnp.asarray(sample))

    abstract = jax.eval_shape(_init)
    leaves = jax.tree.leaves(abstract)
    param_count = sum(int(np.prod(leaf.shape)) for leaf in leaves)
    param_bytes = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize for leaf in leaves)
    dtypes = sorted({str(leaf.dtype) for leaf in leaves})
    # Adam-family training state ≈ params + 2 moments in fp32 + fp32
    # master copy ⇒ ~16 bytes/param upper bound.
    train_state_bytes = param_count * 16
    device = context.devices[0]
    hbm_bytes = 0
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        hbm_bytes = stats.get("bytes_limit", 0)
    return {
        "param_count": param_count,
        "param_bytes": param_bytes,
        "param_dtypes": dtypes,
        "train_state_bytes": train_state_bytes,
        "device_hbm_bytes": hbm_bytes,
        "n_devices": len(context.devices),
        "fits_one_device": (hbm_bytes == 0
                            or train_state_bytes < hbm_bytes * 0.8),
    }
