"""Planner: prune the optimization space into candidate strategies.

Capability parity: atorch Planner (auto/engine/planner.py:13) gating which
optimizations are considered, PLUS the shard planners' axis sizing
(mip_tp_planner.py:30): when the analysis can size axes (HBM known), the
first candidates are model-aware sized configs — fsdp/tensor sizes and
remat derived from the model and device topology — not bare pass names.
"""

from __future__ import annotations

from itertools import combinations
from typing import List

from dlrover_tpu.auto.engine.analyser import analyse, size_axes
from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.opt_lib import SEMIAUTO_STRATEGIES, OptimizationLibrary
from dlrover_tpu.auto.strategy import Strategy


def _pipeline_size(info, n_devices: int) -> int:
    """A sized pipeline candidate is warranted when the model is deep
    enough to cut into balanced stages and its state pressures HBM
    (pipe shards params by depth with one p2p per boundary instead of
    fsdp's per-matmul re-gathers — the winner when the data axis would
    ride a slow fabric). Returns 1 when not warranted."""
    layers = info.get("num_layers", 0) or 0
    if layers < 4 or n_devices < 2 or info["fits_one_device"]:
        return 1
    for stages in (4, 2):
        if n_devices % stages == 0 and layers % stages == 0:
            return stages
    return 1


def _sized_candidates(info, n_devices: int) -> List[Strategy]:
    """Model-aware sized strategies, best-guess first plus neighbors."""
    sizing = size_axes(info)
    # (sequence > 1 implies remat per size_axes's ordering, so these two
    # conditions also cover the long-context case)
    if (sizing["fsdp"] <= 1 and not sizing["remat"]
            and sizing["expert"] <= 1):
        return []

    def build(fsdp: int, tensor: int, remat: bool,
              sequence: int = 1, expert: int = 1,
              pipe: int = 1) -> Strategy:
        strategy: Strategy = [("half", {}), ("module_replace", {})]
        if fsdp > 1:
            strategy.append(("fsdp", {"size": fsdp}))
        if tensor > 1:
            strategy.append(("tensor_parallel", {"size": tensor}))
        if sequence > 1:
            strategy.append(("sequence_parallel", {"size": sequence}))
        if expert > 1:
            strategy.append(("expert_parallel", {"size": expert}))
        if pipe > 1:
            strategy.append(("pipeline_parallel", {"size": pipe}))
        if remat:
            strategy.append(("checkpoint", {}))
        return strategy

    candidates = [build(sizing["fsdp"], sizing["tensor"], sizing["remat"],
                        sizing["sequence"], sizing["expert"])]
    # neighbors: one rung more sharding (cheaper HBM, more comm) and the
    # remat flip, so the dry-run can catch a mis-estimate
    more_fsdp = sizing["fsdp"] * 2
    fixed = (more_fsdp * sizing["tensor"] * sizing["sequence"]
             * sizing["expert"])
    if fixed <= n_devices and n_devices % fixed == 0:
        candidates.append(build(more_fsdp, sizing["tensor"],
                                sizing["remat"], sizing["sequence"],
                                sizing["expert"]))
    candidates.append(build(sizing["fsdp"], sizing["tensor"],
                            not sizing["remat"], sizing["sequence"],
                            sizing["expert"]))
    # depth-sharded alternative: pipeline stages instead of fsdp, the
    # remaining devices on data; MoE configs compose the expert axis
    # INSIDE stages (pipeline_trainer's MoE spec) — the dry-run
    # arbitrates either way
    pipe = _pipeline_size(info, n_devices)
    expert = sizing["expert"]
    if pipe > 1 and n_devices % (pipe * expert) == 0:
        candidates.append(build(1, 1, sizing["remat"], 1, expert, pipe))
    return candidates


def plan_candidates(context: ModelContext,
                    max_candidates: int = 16) -> List[Strategy]:
    info = analyse(context)
    opt_lib = OptimizationLibrary()
    n_devices = info["n_devices"]

    candidates: List[Strategy] = []
    if n_devices > 1:
        candidates.extend(
            _sized_candidates(info, n_devices)[:max_candidates])
    if len(candidates) >= max_candidates:
        return candidates[:max_candidates]

    forced: Strategy = []
    if not info["fits_one_device"] and n_devices > 1:
        forced.append(("fsdp", {}))
    # MoE models must get the expert axis considered: without it every
    # candidate densifies the expert weights onto each device (reference
    # analog: optimization_library registers expert/pipe passes the
    # engine may propose, optimization_library.py:38-53)
    sizing = size_axes(info)
    if sizing["expert"] > 1:
        forced.append(("expert_parallel", {"size": sizing["expert"]}))

    optional: List[str] = []
    for name in SEMIAUTO_STRATEGIES:
        if any(f_name == name for f_name, _ in forced):
            continue
        opt = opt_lib[name]
        if opt.distributed and n_devices < 2:
            continue
        if name == "tensor_parallel" and n_devices % 2:
            continue
        optional.append(name)

    extras: List[Strategy] = []
    if info.get("n_dcn_granules", 1) > 1:
        # multi-slice: the data-axis gradient reduce crosses DCN — plan
        # the int8 compressed reduce as an alternative the dry-run can
        # score against the exact reduce (reference: quant_reduce.cu)
        extras.append(list(forced) + [("half", {}),
                                      ("quant_allreduce", {"bits": 8})])
    pipe = _pipeline_size(info, n_devices)
    if pipe > 1 and n_devices % (pipe * sizing["expert"]) == 0:
        extra: Strategy = [("half", {}), ("module_replace", {})]
        if sizing["expert"] > 1:
            extra.append(("expert_parallel",
                          {"size": sizing["expert"]}))
        extra.append(("pipeline_parallel", {"size": pipe}))
        extras.append(extra)
    if not info["fits_one_device"]:
        # host-offloaded optimizer state: the single-device escape hatch
        # (and an fsdp alternative the dry-run can score)
        extras.append([("half", {}), ("module_replace", {}),
                       ("offload_optimizer", {})])
        if n_devices == 1:
            # offload alone can't save a model whose params+grads exceed
            # HBM — the streaming per-layer trainer caps peak at params
            # + one layer's grads (per-leaf-optimizer contract logged by
            # the pass; the dry-run scores it like any candidate)
            extras.append([("half", {}), ("module_replace", {}),
                           ("streaming", {})])

    # smallest first: baseline (forced only), then singles, then pairs, ...
    for size in range(0, len(optional) + 1):
        for combo in combinations(optional, size):
            if ("fsdp" in combo and "tensor_parallel" in combo
                    and n_devices < 4):
                continue
            strategy = list(forced) + [(name, {}) for name in combo]
            if strategy not in candidates:
                candidates.append(strategy)
                if len(candidates) >= max_candidates:
                    return candidates
        # after the singles round — or right after the baseline when there
        # are no optional passes at all (extras must still be planned)
        if size == min(1, len(optional)):
            for strategy in extras:
                if strategy not in candidates:
                    candidates.append(strategy)
                    if len(candidates) >= max_candidates:
                        return candidates
    return candidates
