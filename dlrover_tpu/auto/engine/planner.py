"""Planner: prune the optimization space into candidate strategies.

Capability parity: atorch Planner (auto/engine/planner.py:13) — analysis
gates which optimizations are even considered (distributed passes need >1
device; fsdp is forced when the train state can't fit one device).
"""

from __future__ import annotations

from itertools import combinations
from typing import List

from dlrover_tpu.auto.engine.analyser import analyse
from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.opt_lib import SEMIAUTO_STRATEGIES, OptimizationLibrary
from dlrover_tpu.auto.strategy import Strategy


def plan_candidates(context: ModelContext,
                    max_candidates: int = 16) -> List[Strategy]:
    info = analyse(context)
    opt_lib = OptimizationLibrary()
    n_devices = info["n_devices"]

    forced: Strategy = []
    if not info["fits_one_device"] and n_devices > 1:
        forced.append(("fsdp", {}))

    optional: List[str] = []
    for name in SEMIAUTO_STRATEGIES:
        if any(f_name == name for f_name, _ in forced):
            continue
        opt = opt_lib[name]
        if opt.distributed and n_devices < 2:
            continue
        if name == "tensor_parallel" and n_devices % 2:
            continue
        optional.append(name)

    candidates: List[Strategy] = []
    # smallest first: baseline (forced only), then singles, then pairs, ...
    for size in range(0, len(optional) + 1):
        for combo in combinations(optional, size):
            if ("fsdp" in combo and "tensor_parallel" in combo
                    and n_devices < 4):
                continue
            strategy = list(forced) + [(name, {}) for name in combo]
            candidates.append(strategy)
            if len(candidates) >= max_candidates:
                return candidates
    return candidates
