"""Strategy search: candidates → dry-run scores → best strategy.

Capability parity: atorch AccelerationEngine + sg_algo
(auto/engine/acceleration_engine.py:34, engine/executor.py:36,
sg_algo/{combination_sg,bo_sg,hebo}). TPU re-design: no worker-process
gRPC fan-out — candidates are dry-run in-process (strategies change mesh/
sharding, which jit handles in one process); the search is successive
halving over small candidate spaces and Gaussian-process Bayesian
optimization (sg_algo.bo_search) when the space outgrows the profiling
budget, with deterministic tie-breaking toward smaller strategies.
"""

from __future__ import annotations

import os
from typing import List, Tuple

from dlrover_tpu.auto.engine.dry_runner import dry_run
from dlrover_tpu.auto.engine.planner import plan_candidates
from dlrover_tpu.auto.engine.sg_algo import bo_search
from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


def _fallback_default(context: ModelContext) -> Strategy:
    logger.warning(
        "every candidate strategy failed to dry-run; falling back "
        "to the default baseline")
    from dlrover_tpu.auto.accelerate import default_strategy

    return default_strategy(len(context.devices))


def search_strategy(
    context: ModelContext,
    max_candidates: int = 0,
    rungs: Tuple[int, ...] = (1, 3),
    keep_fraction: float = 0.5,
    algo: str = "auto",
    budget: int = 0,
) -> Strategy:
    """Pick the best strategy by profiling candidates.

    algo: "sh" = successive halving (profile every candidate briefly,
    keep the top fraction, re-profile longer); "bo" = GP Bayesian
    optimization spending only `budget` dry-runs (sample-efficient for
    large candidate spaces); "auto" = bo when the candidate list
    outgrows the budget, else sh. Overridable via
    DLROVER_TPU_SEARCH_ALGO.
    """
    max_candidates = max_candidates or int(os.environ.get(
        "DLROVER_TPU_SEARCH_MAX_CANDIDATES", 8))
    # explicit arguments win over the env knobs, uniformly
    budget = max(1, budget or int(os.environ.get(
        "DLROVER_TPU_SEARCH_BUDGET") or 6))
    if algo == "auto":
        algo = os.environ.get("DLROVER_TPU_SEARCH_ALGO", "auto")
    algo = algo.strip().lower()
    if algo not in ("auto", "bo", "sh"):
        logger.warning("unknown search algo %r; using successive halving",
                       algo)
        algo = "sh"
    candidates = plan_candidates(context, max_candidates=max_candidates)
    if not candidates:
        return []
    if algo == "auto":
        algo = "bo" if len(candidates) > budget else "sh"
    if algo == "bo":
        best, best_speed, history = bo_search(
            candidates,
            lambda c: dry_run(context, c, warmup=1, steps=rungs[-1])[0],
            budget=budget)
        if best is None:
            return _fallback_default(context)
        logger.info("bo search picked %s (%.2f steps/s, %d/%d profiled)",
                    [name for name, _ in best], best_speed,
                    len(history), len(candidates))
        return best
    scored: List[Tuple[float, int, Strategy]] = [
        (0.0, i, c) for i, c in enumerate(candidates)]
    for steps in rungs:
        results = []
        for _, i, candidate in scored:
            speed, err = dry_run(context, candidate, warmup=1, steps=steps)
            if err:
                logger.info("candidate %s rejected: %s",
                            [n for n, _ in candidate], err[:200])
                continue  # failed candidates never advance a rung
            results.append((speed, i, candidate))
        if not results:
            return _fallback_default(context)
        results.sort(key=lambda t: (-t[0], len(t[2])))
        keep = max(1, int(len(results) * keep_fraction))
        scored = results[:keep]
        if len(scored) == 1:
            break
    best_speed, _, best = scored[0]
    logger.info("search picked %s (%.2f steps/s)",
                [name for name, _ in best], best_speed)
    return best
