"""Strategy search: candidates → dry-run scores → best strategy.

Capability parity: atorch AccelerationEngine + sg_algo
(auto/engine/acceleration_engine.py:34, engine/executor.py:36,
sg_algo/{combination_sg,bo_sg,hebo}). TPU re-design: no worker-process
gRPC fan-out — candidates are dry-run in-process (strategies change mesh/
sharding, which jit handles in one process); the search is successive
halving over the combination space (the BO/HEBO role: sample-efficient
pruning) with deterministic tie-breaking toward smaller strategies.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from dlrover_tpu.auto.engine.dry_runner import dry_run
from dlrover_tpu.auto.engine.planner import plan_candidates
from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


def search_strategy(
    context: ModelContext,
    max_candidates: int = 0,
    rungs: Tuple[int, ...] = (1, 3),
    keep_fraction: float = 0.5,
) -> Strategy:
    """Successive halving: profile every candidate briefly (rungs[0]
    steps), keep the top fraction, re-profile longer, repeat."""
    max_candidates = max_candidates or int(os.environ.get(
        "DLROVER_TPU_SEARCH_MAX_CANDIDATES", 8))
    candidates = plan_candidates(context, max_candidates=max_candidates)
    if not candidates:
        return []
    scored: List[Tuple[float, int, Strategy]] = [
        (0.0, i, c) for i, c in enumerate(candidates)]
    for steps in rungs:
        results = []
        for _, i, candidate in scored:
            speed, err = dry_run(context, candidate, warmup=1, steps=steps)
            if err:
                logger.info("candidate %s rejected: %s",
                            [n for n, _ in candidate], err[:200])
                continue  # failed candidates never advance a rung
            results.append((speed, i, candidate))
        if not results:
            logger.warning(
                "every candidate strategy failed to dry-run; falling back "
                "to the default baseline")
            from dlrover_tpu.auto.accelerate import default_strategy

            return default_strategy(len(context.devices))
        results.sort(key=lambda t: (-t[0], len(t[2])))
        keep = max(1, int(len(results) * keep_fraction))
        scored = results[:keep]
        if len(scored) == 1:
            break
    best_speed, _, best = scored[0]
    logger.info("search picked %s (%.2f steps/s)",
                [name for name, _ in best], best_speed)
    return best
