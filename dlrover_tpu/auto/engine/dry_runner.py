"""Dry-run profiler: score a candidate strategy by actually training.

Capability parity: atorch dry runner (auto/dry_runner/dry_runner.py, used
at accelerate.py:146-148 with ATORCH_DRYRUN_WARMUP_STEP /
PROFILE_STEP envs) — lower the strategy, run warmup + profile steps on a
synthetic batch, return steps/sec. A strategy that fails to lower or OOMs
scores -inf instead of raising (search must survive bad candidates).
"""

from __future__ import annotations

import copy
import os
import time
from typing import Tuple

import jax
import numpy as np

from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.strategy import Strategy
from dlrover_tpu.common.log import default_logger as logger


def _fresh_context(context: ModelContext) -> ModelContext:
    clone = ModelContext(
        context.model,
        optim_factory=context.optim_factory,
        dataset=context.dataset,
        loss_fn=context.loss_fn,
        sample_batch=context.sample_batch,
        optim_args=context.optim_args,
        devices=context.devices,
    )
    clone.plan = copy.deepcopy(context.plan)
    return clone


def dry_run(context: ModelContext, strategy: Strategy,
            warmup: int = 0, steps: int = 0) -> Tuple[float, str]:
    """Returns (steps_per_sec, error). error == "" on success."""
    from dlrover_tpu.auto.accelerate import apply_strategy, lower

    warmup = warmup or int(os.environ.get("DLROVER_TPU_DRYRUN_WARMUP", 1))
    steps = steps or int(os.environ.get("DLROVER_TPU_DRYRUN_STEPS", 3))
    try:
        clone = apply_strategy(_fresh_context(context), strategy)
        result = lower(clone)
        trainer = result.trainer
        state = trainer.init(jax.random.PRNGKey(0))
        sample = np.asarray(
            clone.infer_sample_batch(trainer.micro_batch))
        rng = np.random.default_rng(0)
        vocab_guess = int(sample.max()) + 2
        tokens = rng.integers(0, vocab_guess,
                              (trainer.accum_steps * trainer.micro_batch,)
                              + sample.shape[1:]).astype(sample.dtype)
        tok, tgt = trainer.shard_batch(tokens, tokens)
        for _ in range(max(warmup, 1)):  # ≥1: steps must not time compile
            state, metrics = trainer.step(state, tok, tgt)
        jax.block_until_ready(metrics)
        start = time.perf_counter()
        for _ in range(steps):
            state, metrics = trainer.step(state, tok, tgt)
        jax.block_until_ready(metrics)
        elapsed = time.perf_counter() - start
        return steps / max(elapsed, 1e-9), ""
    except Exception as e:  # noqa: BLE001 - bad candidates must not kill search
        logger.info("dry run failed for %s: %s", [n for n, _ in strategy], e)
        return float("-inf"), str(e)
