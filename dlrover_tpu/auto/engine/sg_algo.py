"""Sample-efficient strategy search: Bayesian optimization over candidates.

Capability parity: atorch's strategy-generation algorithms
(atorch/auto/engine/sg_algo/bo_sg.py, sg_algo/hebo/ — sample-efficient
Bayesian optimization proposing strategy combinations scored by dry-runs).
TPU re-design: the search space is the planner's candidate list (sized +
combinatorial strategies); each candidate is featurized into a small
numeric vector, a Gaussian-process surrogate with an RBF kernel is fit on
the dry-run scores observed so far, and the next candidate to profile is
chosen by expected improvement. Dry-runs are expensive (each one lowers,
compiles, and times real training steps), so the surrogate exists to spend
the profiling budget on the most promising region of the space instead of
exhaustively timing every combination the way successive halving does.

Pure numpy — no sklearn/GPy dependency; the GP is a direct Cholesky solve,
which is plenty for the ≤ a-few-dozen observations a search ever makes.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.auto.strategy import Strategy

# Stable feature vocabulary: every optimization pass the planner can emit.
# Unknown passes hash into the overflow slot so featurize never fails.
_PASS_VOCAB = (
    "half",
    "amp",
    "module_replace",
    "checkpoint",
    "fsdp",
    "zero1",
    "zero2",
    "tensor_parallel",
    "pipeline_parallel",
    "sequence_parallel",
    "expert_parallel",
    "data_parallel",
    "offload_optimizer",
)
_OVERFLOW = len(_PASS_VOCAB)
# vocab + overflow + log2 sizes of every sized axis pass — candidates
# differing only in an axis size must map to distinct feature vectors,
# or the GP treats them as one point and EI never explores the variants
_SIZED_SLOTS = {
    "fsdp": 0, "zero1": 0, "zero2": 0,
    "tensor_parallel": 1,
    "sequence_parallel": 2,
    "expert_parallel": 3,
    "pipeline_parallel": 4,
}
_N_FEATURES = _OVERFLOW + 1 + 1 + max(_SIZED_SLOTS.values())


def featurize(strategy: Strategy) -> np.ndarray:
    """Map a strategy (list of (pass_name, config)) to a fixed vector:
    per-pass indicators plus log2 of each sized axis."""
    x = np.zeros(_N_FEATURES, dtype=np.float64)
    for name, config in strategy:
        try:
            x[_PASS_VOCAB.index(name)] = 1.0
        except ValueError:
            x[_OVERFLOW] = 1.0
        size = int((config or {}).get("size", 0))
        slot = _SIZED_SLOTS.get(name)
        if size > 1 and slot is not None:
            x[_OVERFLOW + 1 + slot] = math.log2(size)
    return x


class GaussianProcess:
    """Minimal RBF-kernel GP regressor (zero mean on z-scored targets).

    Hyperparameters are set by heuristic rather than marginal-likelihood
    optimization: lengthscale = median pairwise distance of the training
    inputs (the classic median heuristic), unit signal variance, small
    noise jitter. With a handful of observations this is as good as
    anything tuned and never diverges.
    """

    def __init__(self, noise: float = 1e-4):
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._lengthscale = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        sq = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * sq / (self._lengthscale ** 2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> "GaussianProcess":
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        z = (y - self._y_mean) / self._y_std
        if len(x) > 1:
            sq = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
            pair = np.sqrt(sq[np.triu_indices(len(x), k=1)])
            med = float(np.median(pair))
            self._lengthscale = med if med > 1e-12 else 1.0
        self._x = x
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, z))
        return self

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev in the ORIGINAL target units."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        k_star = self._kernel(x, self._x)
        mean_z = k_star @ self._alpha
        v = np.linalg.solve(self._chol, k_star.T)
        var_z = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        mean = mean_z * self._y_std + self._y_mean
        std = np.sqrt(var_z) * self._y_std
        return mean, std


def expected_improvement(mean: np.ndarray, std: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI for maximization, closed form under the Gaussian posterior."""
    std = np.maximum(std, 1e-12)
    z = (mean - best - xi) / std
    # standard normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2.0 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    return (mean - best - xi) * cdf + std * pdf


def bo_search(
    candidates: Sequence[Strategy],
    evaluate: Callable[[Strategy], float],
    budget: int,
    n_init: int = 2,
) -> Tuple[Optional[Strategy], float, List[Tuple[float, Strategy]]]:
    """Spend `budget` evaluations over `candidates`, surrogate-guided.

    The first `n_init` evaluations take the planner's own ordering (the
    planner puts its model-aware best guess first, so the seed points are
    informative, not random). Failed evaluations (-inf) are kept in the
    GP's training set at a penalized-but-finite score so the surrogate
    learns to steer away from that region instead of ignoring it.

    Returns (best_strategy_or_None, best_score, history). best is None
    only when every evaluated candidate failed.
    """
    budget = min(budget, len(candidates))
    features = np.stack([featurize(c) for c in candidates])
    evaluated: Dict[int, float] = {}
    history: List[Tuple[float, Strategy]] = []

    def run(i: int) -> None:
        score = float(evaluate(candidates[i]))
        evaluated[i] = score
        history.append((score, candidates[i]))

    for i in range(min(n_init, budget)):
        run(i)

    while len(evaluated) < budget:
        valid = [s for s in evaluated.values() if math.isfinite(s)]
        remaining = [i for i in range(len(candidates)) if i not in evaluated]
        if not remaining:
            break
        if not valid:
            run(remaining[0])  # nothing to model yet: keep seeding
            continue
        floor = min(valid) - 2.0 * (np.std(valid) or abs(min(valid)) or 1.0)
        y = np.array([s if math.isfinite(s) else floor
                      for s in evaluated.values()])
        x = features[list(evaluated.keys())]
        gp = GaussianProcess().fit(x, y)
        mean, std = gp.predict(features[remaining])
        ei = expected_improvement(mean, std, best=max(valid))
        run(remaining[int(np.argmax(ei))])

    finite = [(s, c) for s, c in history if math.isfinite(s)]
    if not finite:
        return None, float("-inf"), history
    # tie-break toward smaller strategies, matching successive halving
    best_score, best = max(finite, key=lambda t: (t[0], -len(t[1])))
    return best, best_score, history
