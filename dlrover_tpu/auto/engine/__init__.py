"""Strategy-search engine (reference: atorch/auto/engine/)."""

from dlrover_tpu.auto.engine.acceleration_engine import search_strategy
from dlrover_tpu.auto.engine.analyser import analyse
from dlrover_tpu.auto.engine.dry_runner import dry_run

__all__ = ["search_strategy", "analyse", "dry_run"]
