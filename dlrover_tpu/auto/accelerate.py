"""auto_accelerate: strategy → lowered sharded trainer.

Capability parity: atorch auto_accelerate (atorch/auto/accelerate.py:391)
and model_transform (:35). Three modes:
- explicit strategy (load_strategy given): apply passes, lower, return —
  the reference's skip-search path;
- semi-auto (strategy="auto"): engine search over SEMIAUTO_STRATEGIES with
  dry-run scoring (engine module);
- default: a sensible TPU baseline (bf16 + flash attention; fsdp when the
  mesh has >1 device).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.opt_lib import OptimizationLibrary
from dlrover_tpu.auto.strategy import (
    Strategy,
    load_strategy,
    normalize_strategy,
    save_strategy,
)
from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import MeshSpec, create_mesh
from dlrover_tpu.parallel.sharding import make_sharding_rules
from dlrover_tpu.trainer.train_step import (
    ShardedTrainer,
    build_trainer,
    choose_accumulation,
)


@dataclasses.dataclass
class AccelerateResult:
    """What auto_accelerate hands back (the reference returns a tuple of
    transformed model/optim/dataloader/loss; here the lowered trainer
    carries them all)."""

    trainer: ShardedTrainer
    mesh: Any
    model: Any
    strategy: Strategy
    context: ModelContext

    # convenience passthroughs
    def init(self, rng):
        return self.trainer.init(rng)

    def step(self, state, tokens, targets):
        return self.trainer.step(state, tokens, targets)


def default_strategy(n_devices: int) -> Strategy:
    strategy: Strategy = [("half", {}), ("module_replace", {})]
    if n_devices > 1:
        strategy.append(("fsdp", {}))
    return strategy


def apply_strategy(context: ModelContext, strategy: Strategy,
                   opt_lib: Optional[OptimizationLibrary] = None
                   ) -> ModelContext:
    """The model_transform analog (accelerate.py:35-66): run each pass."""
    opt_lib = opt_lib or OptimizationLibrary()
    opt_lib.validate_strategy(strategy)
    for name, config in strategy:
        opt_lib[name].apply(context, config)
    return context


def lower(context: ModelContext) -> AccelerateResult:
    """Compile the accumulated plan into a mesh + jitted train step."""
    plan = context.plan
    n_devices = len(context.devices)

    # -- mesh ----------------------------------------------------------
    dims = dict(plan.mesh_dims)
    unknown = sorted(set(dims) - set(MeshAxis.ALL))
    if unknown:
        raise ValueError(
            f"unknown mesh axes {unknown}; valid axes: {MeshAxis.ALL}")
    if plan.fsdp and dims.get(MeshAxis.FSDP, 0) <= 1:
        # fsdp requested without an explicit size: the fsdp axis absorbs
        # every device not claimed by other axes (incl. an explicit data
        # dim; with no data dim, data is pinned to 1 — batch is sharded
        # over (data, fsdp) jointly anyway)
        fixed = 1
        for axis, size in dims.items():
            if axis != MeshAxis.FSDP:
                fixed *= size
        if n_devices % fixed == 0 and n_devices // fixed > 1:
            dims[MeshAxis.FSDP] = n_devices // fixed
            dims.setdefault(MeshAxis.DATA, 1)
    spec = MeshSpec(**dims)
    mesh = create_mesh(spec, context.devices)

    # -- model edits (dataclass-config models) -------------------------
    updates = {}
    if plan.compute_dtype is not None:
        updates["dtype"] = plan.compute_dtype
    if plan.params_dtype is not None:
        updates["param_dtype"] = plan.params_dtype
    if plan.flash_attention:
        updates["attn_impl"] = (
            "flash" if jax.default_backend() == "tpu" else "reference")
    if plan.sequence_parallel and mesh.shape[MeshAxis.SEQUENCE] > 1:
        # SP replaces the attention kernel: the sequence dim is sharded, so
        # attention must be the ring/all-to-all implementation (wins over a
        # flash_attention request — the Pallas kernel needs the full seq).
        updates["attn_impl"] = plan.sequence_impl
    if plan.remat:
        updates["remat"] = True
        if plan.remat_policy:
            updates["remat_policy"] = plan.remat_policy
    if updates:
        skipped = context.replace_model_config(**updates)
        if skipped is None:
            logger.info(
                "model has no dataclass cfg; edits %s skipped (strategy "
                "still shapes mesh + shardings)", sorted(updates))
        elif skipped:
            # a partially-supported config is a memory-plan hazard: the
            # sizing may have counted on the dropped edit (remat, SP)
            logger.warning(
                "model config does not accept %s; those edits were "
                "dropped (applied: %s)", skipped,
                sorted(set(updates) - set(skipped)))

    # -- sharding rules -------------------------------------------------
    rules = make_sharding_rules(
        fsdp=plan.fsdp and mesh.shape[MeshAxis.FSDP] > 1,
        tensor=plan.tensor_parallel and mesh.shape[MeshAxis.TENSOR] > 1,
        extra=plan.rule_overrides,
    )

    # -- batch geometry --------------------------------------------------
    from dlrover_tpu.parallel.mesh import dp_size as mesh_dp_size

    dp = mesh_dp_size(mesh)
    if plan.global_batch:
        accum, micro_global = choose_accumulation(
            plan.global_batch, dp,
            max_micro_per_replica=plan.micro_batch or 64)
        micro = micro_global
    else:
        accum = plan.accum_steps
        micro = plan.micro_batch or dp
    sample = context.infer_sample_batch(micro)

    if plan.streaming:
        from dlrover_tpu.models.llama import (
            LlamaConfig,
            cross_entropy_loss,
        )
        from dlrover_tpu.trainer.streaming import build_streaming_trainer

        if n_devices > 1 or plan.pipeline_stages > 1:
            raise ValueError(
                "streaming is the single-device >HBM escape hatch; on "
                f"{n_devices} devices use fsdp / pipeline_parallel "
                "instead (they shard the gradient tree across chips)")
        if accum > 1:
            raise ValueError(
                f"streaming cannot gradient-accumulate (accum={accum}): "
                "holding the accumulated full-tree gradients is exactly "
                "the >HBM cost streaming exists to avoid — raise "
                "micro_batch (or drop global_batch) so accum == 1")
        cfg = context.model_config()
        if not isinstance(cfg, LlamaConfig):
            raise NotImplementedError(
                "streaming lowering needs the scan-shaped Llama stack "
                "(LlamaConfig); for custom models call "
                "dlrover_tpu.trainer.streaming.build_streaming_trainer "
                "with a compatible per-layer model directly")
        if context.loss_fn not in (None, cross_entropy_loss):
            logger.warning(
                "streaming computes its own chunked cross-entropy head "
                "loss; the provided loss_fn is ignored")
        trainer = build_streaming_trainer(
            cfg, context.make_optimizer(),
            micro_batch=micro,
            seq_len=int(np.asarray(sample).shape[-1]),
            devices=context.devices,
        )
        return AccelerateResult(trainer=trainer, mesh=trainer.mesh,
                                model=context.model, strategy=[],
                                context=context)

    if plan.pipeline_stages > 1:
        from dlrover_tpu.models.bert import BertConfig
        from dlrover_tpu.models.gpt import GPTConfig
        from dlrover_tpu.models.llama import LlamaConfig
        from dlrover_tpu.trainer.pipeline_trainer import (
            build_pipeline_trainer,
        )

        cfg = context.model_config()
        if not isinstance(cfg, (LlamaConfig, GPTConfig, BertConfig)):
            raise NotImplementedError(
                "pipeline lowering needs a stacked-block model config "
                "(LlamaConfig, GPTConfig, or BertConfig); for custom "
                "models build a PipelineModelSpec and a PipelinedTrainer "
                "directly (dlrover_tpu.trainer.pipeline_trainer)")
        if plan.global_batch:
            # the accumulation geometry IS the microbatch stream: the
            # user's global batch is authoritative (accum × micro rows)
            num_micro = accum
        else:
            num_micro = max(plan.accum_steps, 2 * plan.pipeline_stages)
        if plan.grad_reduce_bits:
            logger.warning(
                "quant_allreduce is not implemented for the pipeline "
                "trainer: the data-axis gradient reduce stays exact "
                "(grad_reduce_bits=%d ignored under "
                "pipeline_parallel)", plan.grad_reduce_bits)
        trainer = build_pipeline_trainer(
            cfg, context.make_optimizer(), mesh,
            num_microbatches=num_micro, micro_batch=micro,
            seq_len=np.asarray(sample).shape[-1],
            loss_fn=context.loss_fn, remat=plan.remat,
            num_rounds=plan.pipeline_rounds,
            rules=rules,
            offload_opt_state=plan.offload_optimizer,
            bound_activations=plan.pipeline_bound_activations,
        )
        return AccelerateResult(trainer=trainer, mesh=mesh,
                                model=context.model, strategy=[],
                                context=context)

    trainer = build_trainer(
        context.model,
        context.make_optimizer(),
        mesh,
        np.asarray(sample),
        context.loss_fn,
        accum_steps=accum,
        micro_batch=micro,
        rules=rules,
        donate_state=plan.donate_state,
        offload_opt_state=plan.offload_optimizer,
        grad_reduce_bits=plan.grad_reduce_bits,
    )
    return AccelerateResult(trainer=trainer, mesh=mesh,
                            model=context.model, strategy=[],
                            context=context)


def auto_accelerate(
    model: Any,
    optim_factory: Optional[Callable] = None,
    dataset: Optional[Any] = None,
    loss_fn: Optional[Callable] = None,
    *,
    sample_batch: Optional[Any] = None,
    strategy: Optional[Any] = None,
    load_strategy_file: str = "",
    save_strategy_to_file: str = "",
    global_batch: int = 0,
    micro_batch: int = 0,
    devices: Optional[Sequence[jax.Device]] = None,
    optim_args: Optional[dict] = None,
) -> AccelerateResult:
    """One-call acceleration (atorch auto_accelerate parity).

    strategy: None → default TPU baseline; "auto" → engine search;
    list → explicit strategy (names or (name, config) pairs).
    """
    context = ModelContext(
        model, optim_factory=optim_factory, dataset=dataset,
        loss_fn=loss_fn, sample_batch=sample_batch,
        optim_args=optim_args, devices=devices,
    )
    context.plan.global_batch = global_batch
    context.plan.micro_batch = micro_batch

    if load_strategy_file:
        chosen = load_strategy(load_strategy_file)
    elif strategy == "auto":
        from dlrover_tpu.auto.engine.acceleration_engine import (
            search_strategy,
        )

        chosen = search_strategy(context)
    elif strategy is not None:
        chosen = normalize_strategy(strategy)
    else:
        chosen = default_strategy(len(context.devices))

    apply_strategy(context, chosen)
    result = lower(context)
    result.strategy = chosen
    if save_strategy_to_file:
        save_strategy(chosen, save_strategy_to_file)
    return result
