"""Optimization library: named, composable acceleration passes.

Capability parity: atorch OptimizationLibrary
(atorch/auto/opt_lib/optimization_library.py:38-53) and its 13 registered
optimizations. Mapping to TPU-native semantics:

| atorch name        | here               | effect on the plan            |
|--------------------|--------------------|-------------------------------|
| parallel_mode      | parallel_mode      | mesh data dim (DDP ≙ pure DP) |
| zero1/zero2/fsdp   | zero1/zero2/fsdp   | fsdp axis shards params/opt   |
| amp_native         | amp                | bf16 compute, fp32 params     |
| half               | half               | bf16 everywhere               |
| checkpoint         | remat / checkpoint | jax.checkpoint policy         |
| module_replace     | module_replace     | Pallas flash-attention kernel |
| tensor_parallel    | tensor_parallel    | tensor axis via rule table    |
| pipeline_parallel  | pipeline_parallel  | pipe axis, staged scan        |
| mixed_parallel     | mixed_parallel     | arbitrary named dims          |
| ds_3d_parallel     | 3d_parallel        | data×tensor×pipe preset       |
| (sequence module)  | sequence_parallel  | sequence axis ring attention  |
| (moe module)       | expert_parallel    | expert axis all-to-all        |
"""

from dlrover_tpu.auto.opt_lib.library import (
    Optimization,
    OptimizationLibrary,
    SEMIAUTO_STRATEGIES,
)

__all__ = ["Optimization", "OptimizationLibrary", "SEMIAUTO_STRATEGIES"]
