"""The optimization registry and every built-in pass."""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.common.log import default_logger as logger


class Optimization:
    """One named pass editing the plan. `distributed` passes need >1
    device to be meaningful; the planner uses this for pruning."""

    name: str = ""
    distributed: bool = False
    # mutually-exclusive group (e.g. only one of zero1/zero2/fsdp)
    group: str = ""

    def apply(self, context: ModelContext, config: dict) -> None:
        raise NotImplementedError


def _set_mesh_dim(context: ModelContext, axis: str, size: int) -> None:
    context.plan.mesh_dims[axis] = size


class ParallelModeOptimization(Optimization):
    """Pure data parallelism (DDP analog). config: {"data": N} or empty
    (data absorbs all devices)."""

    name = "parallel_mode"
    distributed = True

    def apply(self, context, config):
        if "data" in config:
            _set_mesh_dim(context, MeshAxis.DATA, int(config["data"]))


class Zero1Optimization(Optimization):
    """Optimizer-state sharding. On TPU the fsdp axis shards params AND
    optimizer state (XLA re-gathers weights as needed); zero1/zero2/fsdp
    differ only in how much of the rule table they move to the fsdp axis —
    kept as separate names for strategy parity."""

    name = "zero1"
    distributed = True
    group = "zero"

    def apply(self, context, config):
        context.plan.fsdp = True
        size = int(config.get("size", 0))
        if size:
            _set_mesh_dim(context, MeshAxis.FSDP, size)


class Zero2Optimization(Zero1Optimization):
    name = "zero2"


class FSDPOptimization(Zero1Optimization):
    name = "fsdp"


class AmpOptimization(Optimization):
    """bf16 compute with fp32 master params (native-AMP analog — TPUs use
    bf16, no loss scaling needed: bf16 has fp32's exponent range)."""

    name = "amp"

    def apply(self, context, config):
        context.plan.compute_dtype = jnp.bfloat16
        context.plan.params_dtype = jnp.float32


class HalfOptimization(Optimization):
    """Everything in bf16 (atorch half 'bf16')."""

    name = "half"

    def apply(self, context, config):
        dtype = config.get("dtype", "bfloat16")
        context.plan.compute_dtype = jnp.dtype(dtype)
        context.plan.params_dtype = jnp.dtype(dtype)


class RematOptimization(Optimization):
    """Activation checkpointing via jax.checkpoint (atorch 'checkpoint')."""

    name = "checkpoint"

    def apply(self, context, config):
        context.plan.remat = True
        context.plan.remat_policy = config.get("policy", "full")


class ModuleReplaceOptimization(Optimization):
    """Swap attention for the Pallas flash kernel (atorch module_replace
    pairs BertAttention→FlashAttn etc.)."""

    name = "module_replace"

    def apply(self, context, config):
        context.plan.flash_attention = True


class OffloadOptimizerOptimization(Optimization):
    """Optimizer state in host memory (reference: Adam w/ CPU offload,
    atorch/optim/adam_offload.py). TPU re-design: the moments' shardings
    carry the pinned_host memory kind; XLA inserts the host↔HBM
    transfers around the update — no custom optimizer needed."""

    name = "offload_optimizer"

    def apply(self, context, config):
        context.plan.offload_optimizer = True


class StreamingOptimization(Optimization):
    """Per-layer streaming backward+update: train models whose FULL
    gradient tree exceeds one device's HBM (reference capability:
    FSDP param/grad sharding, atorch/distributed/zero_optimization.py:215,
    and CPU-offloaded Adam, atorch/optim/adam_offload.py — this is the
    single-chip TPU analog). The backward runs as a reverse per-layer
    loop applying the optimizer update in place, so peak memory is
    params + ONE layer's gradients (trainer/streaming.py).

    Contract: scan-shaped Llama stack + a PER-LEAF optimizer
    (factored_rms/adafactor/adam qualify; global-norm clipping does
    not — its norm would be per-layer, changing the math)."""

    name = "streaming"

    def apply(self, context, config):
        context.plan.streaming = True
        logger.info(
            "streaming: per-layer backward+update — the optimizer must "
            "be per-leaf (factored_rms/adafactor; global-norm clipping "
            "would silently become per-layer clipping)")


class QuantizedAllreduceOptimization(Optimization):
    """int8/int4 groupwise gradient all-reduce over the data/DCN axis
    (reference: the quant_reduce CUDA kernel,
    atorch/ops/csrc/quantization/quant_reduce.cu:248 — dequantize N
    partitions, reduce, requantize for the wire). On multi-slice meshes
    the data-axis gradient reduce rides DCN (`_dcn_split`,
    parallel/mesh.py) and is the bandwidth bottleneck this compresses.
    config: {"bits": 8|4}."""

    name = "quant_allreduce"
    distributed = True

    def apply(self, context, config):
        bits = int(config.get("bits", 8))
        if bits not in (8, 4):
            raise ValueError(
                f"quant_allreduce bits must be 8 or 4, got {bits}")
        context.plan.grad_reduce_bits = bits


class TensorParallelOptimization(Optimization):
    """Megatron-style TP: column/row splits come from the logical-axis rule
    table, no module surgery. config: {"size": N}."""

    name = "tensor_parallel"
    distributed = True

    def apply(self, context, config):
        context.plan.tensor_parallel = True
        _set_mesh_dim(context, MeshAxis.TENSOR,
                      int(config.get("size", 2)))


class SequenceParallelOptimization(Optimization):
    """Ring attention over a sequence axis (atorch
    DistributedSelfAttention analog). config: {"size": N}."""

    name = "sequence_parallel"
    distributed = True

    def apply(self, context, config):
        context.plan.sequence_parallel = True
        impl = config.get("impl", "ring")
        if impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel impl must be ring|ulysses, got {impl!r}")
        context.plan.sequence_impl = impl
        _set_mesh_dim(context, MeshAxis.SEQUENCE,
                      int(config.get("size", 2)))


class ExpertParallelOptimization(Optimization):
    """MoE expert-parallel axis. config: {"size": N}."""

    name = "expert_parallel"
    distributed = True

    def apply(self, context, config):
        context.plan.expert_parallel = True
        _set_mesh_dim(context, MeshAxis.EXPERT,
                      int(config.get("size", 2)))


class PipelineParallelOptimization(Optimization):
    """Stage-sharded pipeline over the pipe axis. config: {"size": N}."""

    name = "pipeline_parallel"
    distributed = True

    def apply(self, context, config):
        size = int(config.get("size", 2))
        context.plan.pipeline_stages = size
        # rounds > 1 = circular/interleaved schedule (bubble ÷ rounds)
        context.plan.pipeline_rounds = int(config.get("rounds", 1))
        # 1F1B-style live-activation bound (checkpointed step windows)
        context.plan.pipeline_bound_activations = bool(
            config.get("memory_bound", False))
        _set_mesh_dim(context, MeshAxis.PIPE, size)


class MixedParallelOptimization(Optimization):
    """Arbitrary named dims: config {"dims": [["tensor",4],["data",2]]}
    (atorch create_parallel_group spec,
    atorch/distributed/distributed.py:323-334)."""

    name = "mixed_parallel"
    distributed = True

    def apply(self, context, config):
        for name, size in config.get("dims", []):
            _set_mesh_dim(context, name, int(size))
            if name == MeshAxis.FSDP:
                context.plan.fsdp = True
            elif name == MeshAxis.TENSOR:
                context.plan.tensor_parallel = True
            elif name == MeshAxis.SEQUENCE:
                context.plan.sequence_parallel = True
            elif name == MeshAxis.EXPERT:
                context.plan.expert_parallel = True
            elif name == MeshAxis.PIPE:
                context.plan.pipeline_stages = int(size)


class ThreeDParallelOptimization(Optimization):
    """data×tensor×pipe preset (DeepSpeed 3D analog). config:
    {"data": D, "tensor": T, "pipe": P}."""

    name = "3d_parallel"
    distributed = True

    def apply(self, context, config):
        MixedParallelOptimization().apply(context, {"dims": [
            [MeshAxis.DATA, config.get("data", 1)],
            [MeshAxis.TENSOR, config.get("tensor", 2)],
            [MeshAxis.PIPE, config.get("pipe", 2)],
        ]})


class OptimizationLibrary:
    """Name → Optimization registry (atorch
    OptimizationLibrary.register_optimizations)."""

    def __init__(self):
        self.opts: Dict[str, Optimization] = {}
        for opt_cls in (
            ParallelModeOptimization,
            Zero1Optimization,
            Zero2Optimization,
            FSDPOptimization,
            AmpOptimization,
            HalfOptimization,
            RematOptimization,
            ModuleReplaceOptimization,
            TensorParallelOptimization,
            SequenceParallelOptimization,
            ExpertParallelOptimization,
            PipelineParallelOptimization,
            MixedParallelOptimization,
            ThreeDParallelOptimization,
            OffloadOptimizerOptimization,
            QuantizedAllreduceOptimization,
            StreamingOptimization,
        ):
            opt = opt_cls()
            self.opts[opt.name] = opt
        # atorch aliases
        self.opts["remat"] = self.opts["checkpoint"]
        self.opts["amp_native"] = self.opts["amp"]
        self.opts["adam_offload"] = self.opts["offload_optimizer"]

    def __getitem__(self, name: str) -> Optimization:
        return self.opts[name]

    def __contains__(self, name: str) -> bool:
        return name in self.opts

    def validate_strategy(self, strategy) -> None:
        seen_groups: Dict[str, str] = {}
        for name, _ in strategy:
            if name not in self.opts:
                raise ValueError(
                    f"unknown optimization {name!r}; "
                    f"available: {sorted(self.opts)}")
            group = self.opts[name].group
            if group:
                if group in seen_groups:
                    raise ValueError(
                        f"optimizations {seen_groups[group]!r} and "
                        f"{name!r} are mutually exclusive")
                seen_groups[group] = name


# Strategies the semi-auto mode will combine and dry-run (atorch
# SEMIAUTO_STRATEGIES, optimization_library.py:13).
SEMIAUTO_STRATEGIES = (
    "amp",
    "checkpoint",
    "module_replace",
    "fsdp",
    "tensor_parallel",
)
