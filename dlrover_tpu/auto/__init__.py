"""Auto-acceleration: one-call strategy → lowered sharded trainer.

Capability parity: atorch's `auto_accelerate` stack (atorch/auto/
accelerate.py:391, model_context.py, opt_lib/optimization_library.py:38-53).
TPU re-design: an optimization does not wrap modules — it edits an
AccelerationPlan (mesh spec, logical-axis sharding rules, dtypes, remat
policy, kernel choices, grad accumulation), and one final lowering compiles
the whole plan into a jitted sharded train step. Strategies are declarative
data, savable/loadable like atorch's strategy files.
"""

from dlrover_tpu.auto.accelerate import AccelerateResult, auto_accelerate
from dlrover_tpu.auto.model_context import ModelContext
from dlrover_tpu.auto.strategy import Strategy, load_strategy, save_strategy
from dlrover_tpu.auto.opt_lib import OptimizationLibrary

__all__ = [
    "AccelerateResult",
    "ModelContext",
    "OptimizationLibrary",
    "Strategy",
    "auto_accelerate",
    "load_strategy",
    "save_strategy",
]
