"""ModelContext + AccelerationPlan.

Capability parity: atorch ModelContext (atorch/auto/model_context.py) —
carries model/optim/dataset/loss through the optimization passes. The TPU
difference: passes edit the declarative `AccelerationPlan` (mesh axes,
sharding-rule table, dtypes, remat, kernels, accumulation) instead of
wrapping the model; `lower()` compiles the final plan once.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


@dataclasses.dataclass
class AccelerationPlan:
    """Everything the final lowering needs, as plain data."""

    # mesh: name → size; data absorbs the remainder when 0
    mesh_dims: Dict[str, int] = dataclasses.field(default_factory=dict)
    # logical-axis → mesh-axis overrides appended to the rule table
    rule_overrides: List[Tuple[str, Optional[str]]] = dataclasses.field(
        default_factory=list)
    fsdp: bool = False
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    # "ring" (ppermute KV rotation) | "ulysses" (all-to-all head parallel)
    sequence_impl: str = "ring"
    expert_parallel: bool = False
    pipeline_stages: int = 1
    # circular (interleaved) schedule: layer chunks per stage; 1 = GPipe
    pipeline_rounds: int = 1
    compute_dtype: Optional[Any] = None      # jnp.bfloat16 for half/amp
    params_dtype: Optional[Any] = None       # fp32 master params when amp
    remat: bool = False
    remat_policy: str = ""                   # "" | "full" | "dots" | "nothing_saveable"
    flash_attention: bool = False
    accum_steps: int = 1
    micro_batch: int = 0                     # 0 = derive from global batch
    global_batch: int = 0
    donate_state: bool = True
    # optimizer moments in host memory (reference: adam_offload)
    offload_optimizer: bool = False
    # 8/4 = int-quantized gradient all-reduce over the data/DCN axis
    # (reference: quant_reduce.cu); 0 = exact
    grad_reduce_bits: int = 0
    # 1F1B-style live-activation bound for PP (checkpointed windows)
    pipeline_bound_activations: bool = False
    # per-layer streaming backward+update: >HBM models on ONE device
    # (reference: FSDP param/grad sharding + adam_offload are its
    # multi-device / host-memory analogs)
    streaming: bool = False
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


class ModelContext:
    """Mutable carrier through optimization passes."""

    def __init__(
        self,
        model: Any,
        optim_factory: Optional[Callable[..., Any]] = None,
        dataset: Optional[Any] = None,
        loss_fn: Optional[Callable] = None,
        sample_batch: Optional[Any] = None,
        optim_args: Optional[dict] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.model = model
        self.optim_factory = optim_factory
        self.optim_args = dict(optim_args or {})
        self.dataset = dataset
        self.loss_fn = loss_fn
        self.sample_batch = sample_batch
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.plan = AccelerationPlan()
        # wrappers applied to the model's apply fn at lowering (in order)
        self.apply_transforms: List[Callable] = []

    # -- model-config editing (models expose a dataclass config) ---------
    def model_config(self):
        for attr in ("config", "cfg"):
            cfg = getattr(self.model, attr, None)
            if cfg is not None and dataclasses.is_dataclass(cfg):
                return cfg
        return None

    def replace_model_config(self, **updates):
        """For framework models (dataclass cfg): rebuild with new config.

        Applies the SUPPORTED subset of updates (a config missing one
        field must not lose the others — e.g. a model without `remat`
        still gets its dtype and attention kernel set). Returns the list
        of skipped keys (empty = everything applied), or None when the
        model doesn't expose a dataclass config at all."""
        cfg = self.model_config()
        if cfg is None or not dataclasses.is_dataclass(cfg):
            return None
        valid = {f.name for f in dataclasses.fields(cfg)}
        usable = {k: v for k, v in updates.items() if k in valid}
        if usable:
            new_cfg = dataclasses.replace(cfg, **usable)
            self.model = type(self.model)(new_cfg)
        return sorted(set(updates) - set(usable))

    def make_optimizer(self):
        import optax

        if self.optim_factory is None:
            return optax.adamw(3e-4)
        return self.optim_factory(**self.optim_args)

    def infer_sample_batch(self, micro_batch: int):
        """A (micro_batch, seq)-shaped sample for shape inference."""
        if self.sample_batch is not None:
            sample = np.asarray(self.sample_batch)
            if sample.shape[0] != micro_batch:
                reps = int(np.ceil(micro_batch / sample.shape[0]))
                sample = np.tile(sample, (reps,) + (1,) * (sample.ndim - 1))
                sample = sample[:micro_batch]
            return sample
        raise ValueError("sample_batch is required for lowering")
