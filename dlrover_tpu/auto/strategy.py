"""Strategy model: a named list of optimizations with configs.

Capability parity: atorch strategy save/load
(auto_accelerate(load_strategy=..., save_strategy_to_file=...),
atorch/auto/accelerate.py:408) — JSON on disk, `[(name, config), ...]` in
memory.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

Strategy = List[Tuple[str, Dict[str, Any]]]


def normalize_strategy(strategy) -> Strategy:
    """Accept ["fsdp", ("amp", {...})] shorthand."""
    out: Strategy = []
    for item in strategy:
        if isinstance(item, str):
            out.append((item, {}))
        else:
            name, config = item
            out.append((name, dict(config or {})))
    return out


def save_strategy(strategy: Strategy, path: str) -> None:
    with open(path, "w") as f:
        json.dump([[name, config] for name, config in strategy], f,
                  indent=2)


def load_strategy(path: str) -> Strategy:
    with open(path) as f:
        raw = json.load(f)
    return [(name, dict(config)) for name, config in raw]
