"""Streaming per-layer training step: >HBM models on one chip.

Capability parity: the reference trains models whose full gradient set
does not fit device memory via FSDP param/grad sharding
(atorch/atorch/distributed/zero_optimization.py:215) and CPU-offloaded
Adam (atorch/atorch/optim/adam_offload.py). On a single TPU chip the
same wall is the *simultaneous* gradient tree: a standard
``jax.value_and_grad`` step materializes every layer's gradient at once,
so bf16 Llama-7B needs params (13.5 GB) + grads (13.5 GB) > 15.75 GB
HBM. TPU re-design: per-leaf optimizers (adafactor family) don't need
the whole gradient tree — so this trainer hand-orchestrates the backward
pass as a reverse ``fori_loop`` over layers, where each iteration

    1. recomputes the layer forward from its stashed input (remat),
    2. runs the layer-local VJP,
    3. applies the optimizer update to that layer in place
       (``dynamic_update_index_in_dim`` on the loop carry — XLA's
       in-place loop-carry aliasing keeps ONE params buffer live),
    4. frees the layer gradient by construction (it dies with the loop
       iteration).

Peak memory: params + ONE layer's grads + the layer-input stash
(L, micro, seq, hidden) — ~14.5 GB for 7B at micro 1 / seq 2048, which
fits. The math is identical to the dense step: every layer's VJP uses
the pre-update params (updates touch only already-differentiated
layers), so the result matches ``build_trainer``'s step bit-for-bit up
to float reassociation (asserted by tests/test_streaming.py).

Constraints: the model is the scan-shaped Llama stack (identical
decoder blocks); the optimizer must be per-leaf (no cross-leaf state —
factored_rms/adafactor qualify, global-norm clipping does not, which is
why it takes an explicit ``tx`` and documents the contract).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models.llama import (
    DecoderBlock,
    LlamaConfig,
    RMSNorm,
    embed_lookup,
)


@flax.struct.dataclass
class StreamingState:
    step: jax.Array
    block_params: Any        # every leaf stacked with leading dim L
    embed: jax.Array         # (vocab, hidden)
    head: Optional[jax.Array]  # (hidden, vocab); None = tied to embed
    norm_params: Any         # final RMSNorm params
    block_opt: Any           # per-layer optimizer state, stacked
    embed_opt: Any
    head_opt: Any
    norm_opt: Any

    @property
    def params(self) -> Any:
        """Parameter subtree (TrainState.params parity) so generic
        consumers — e.g. the elastic loop's model-info report — can
        size the model without knowing the streaming layout."""
        return {"blocks": self.block_params, "embed": self.embed,
                "head": self.head, "norm": self.norm_params}


def _tree_index(tree: Any, i) -> Any:
    return jax.tree.map(
        lambda x: jax.lax.dynamic_index_in_dim(x, i, 0, keepdims=False),
        tree)


def _tree_update(tree: Any, leaf_tree: Any, i) -> Any:
    return jax.tree.map(
        lambda x, v: jax.lax.dynamic_update_index_in_dim(
            x, v.astype(x.dtype), i, 0),
        tree, leaf_tree)


@dataclasses.dataclass
class StreamingTrainer:
    """Mirror of ShardedTrainer's surface for the streaming step —
    including `mesh` and `precompile()`, so ElasticTrainLoop can take it
    as an injected trainer (checkpoint/resume, restore-compile overlap,
    speed reports all apply unchanged)."""

    config: LlamaConfig
    init_fn: Callable[[jax.Array], StreamingState]
    step_fn: Callable[..., Tuple[StreamingState, dict]]
    micro_batch: int
    seq_len: int
    accum_steps: int = 1
    mesh: Any = None                 # single-device mesh
    precompile_timings: dict = dataclasses.field(default_factory=dict)
    _compiled: Any = None

    def init(self, rng: jax.Array) -> StreamingState:
        return self.init_fn(rng)

    def abstract_state(self, rng: jax.Array) -> StreamingState:
        return jax.eval_shape(self.init_fn, rng)

    def precompile(self, rng: Optional[jax.Array] = None) -> None:
        """AOT-compile the step for the built shapes (idempotent); the
        elastic loop calls this concurrently with the checkpoint read."""
        if self._compiled is not None:
            return
        import time as _time

        t0 = _time.monotonic()
        abstract = self.abstract_state(
            rng if rng is not None else jax.random.PRNGKey(0))
        tok = jax.ShapeDtypeStruct((self.micro_batch, self.seq_len),
                                   jnp.int32)
        self._compiled = self.step_fn.lower(abstract, tok, tok).compile()
        self.precompile_timings = {
            "streaming_aot_s": round(_time.monotonic() - t0, 2)}

    def step(self, state: StreamingState, tokens, targets):
        # the AOT executable is shape-pinned; any other shape (shorter
        # final batch, a longer sequence) takes the jitted path, which
        # retraces — the head-loss chunking derives from the runtime
        # length, so other sequence lengths stay supported
        if (self._compiled is not None
                and tuple(tokens.shape) == (self.micro_batch,
                                            self.seq_len)):
            return self._compiled(state, tokens, targets)
        return self.step_fn(state, tokens, targets)

    def shard_batch(self, tokens, targets):
        return jnp.asarray(tokens), jnp.asarray(targets)


def build_streaming_trainer(
    cfg: LlamaConfig,
    tx: optax.GradientTransformation,
    micro_batch: int,
    seq_len: int,
    rng_seed: int = 0,
    devices: Any = None,
) -> StreamingTrainer:
    """Lower a scan-shaped Llama + per-leaf optimizer into a streaming
    step. Single-device oriented (the >HBM single-chip escape hatch);
    multi-chip scale-out composes the ordinary trainers with FSDP/PP."""
    L = cfg.num_layers
    hidden = cfg.hidden_size
    block = DecoderBlock(cfg)
    norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.norm_impl)

    x0 = jax.ShapeDtypeStruct((micro_batch, seq_len, hidden), cfg.dtype)
    pos0 = jax.ShapeDtypeStruct((micro_batch, seq_len), jnp.int32)
    block_abstract = jax.eval_shape(
        lambda k, x, p: block.init(k, x, p),
        jax.random.key(0), x0, pos0)["params"]
    norm_abstract = jax.eval_shape(
        lambda k, x: norm.init(k, x), jax.random.key(0), x0)["params"]

    def _init_leaf(key, a, path):
        name = "/".join(str(p) for p in path).lower()
        # norm scales init to ones (models/llama.py RMSNorm uses
        # nn.initializers.ones); they are the only 1-D params in the
        # stack, so the rank check catches the bare "weight" path of the
        # final norm too
        if "norm" in name or "scale" in name or len(a.shape) == 1:
            return jnp.ones(a.shape, a.dtype)
        return (jax.random.normal(key, a.shape, jnp.float32) * 0.02
                ).astype(a.dtype)

    def _init(rng) -> StreamingState:
        leaves, treedef = jax.tree_util.tree_flatten_with_path(
            block_abstract)
        keys = jax.random.split(jax.random.fold_in(rng, 0),
                                len(leaves) * L)
        stacked = []
        for n, (path, a) in enumerate(leaves):
            per_layer = [
                _init_leaf(keys[n * L + layer], a, path)
                for layer in range(L)
            ]
            stacked.append(jnp.stack(per_layer))
        block_params = jax.tree.unflatten(
            jax.tree.structure(block_abstract), stacked)
        embed = (jax.random.normal(
            jax.random.fold_in(rng, 1), (cfg.vocab_size, hidden),
            jnp.float32) * 0.02).astype(cfg.param_dtype)
        head = None
        if not cfg.tie_embeddings:
            head = (jax.random.normal(
                jax.random.fold_in(rng, 2), (hidden, cfg.vocab_size),
                jnp.float32) * 0.02).astype(cfg.param_dtype)
        norm_params = jax.tree_util.tree_map_with_path(
            lambda p, a: _init_leaf(jax.random.fold_in(rng, 3), a, p),
            norm_abstract)
        return StreamingState(
            step=jnp.zeros((), jnp.int32),
            block_params=block_params,
            embed=embed,
            head=head,
            norm_params=norm_params,
            block_opt=jax.vmap(tx.init)(block_params),
            embed_opt=tx.init(embed),
            head_opt=None if head is None else tx.init(head),
            norm_opt=tx.init(norm_params),
        )

    def _apply_update(params, grads, opt_state):
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def _step(state: StreamingState, tokens, targets):
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[-1]), tokens.shape)

        # ---- forward: loop over layers, stash each layer's INPUT -----
        h = embed_lookup(state.embed, tokens, cfg)
        stash = jnp.zeros((L,) + h.shape, h.dtype)

        def fwd_body(i, carry):
            h, stash = carry
            stash = jax.lax.dynamic_update_index_in_dim(stash, h, i, 0)
            p_i = _tree_index(state.block_params, i)
            h = block.apply({"params": p_i}, h, positions)
            return h, stash

        h, stash = jax.lax.fori_loop(0, L, fwd_body, (h, stash))

        # ---- head + final norm: ordinary VJP (small params) ----------
        head_param = state.embed if state.head is None else state.head
        # chunk the (seq, vocab) logits over sequence with per-chunk
        # recompute: peak logits memory = one chunk, not B*S*V fp32
        # (for 7B at seq 2048 that's ~790 MB of softmax temps saved)

        def head_loss(norm_params, head_p, h):
            x = norm.apply({"params": norm_params}, h)
            w = head_p.astype(cfg.dtype)
            wt = w.T if state.head is None else w
            b, s, hid = x.shape
            # chunk from the RUNTIME length (trace-time static), so any
            # sequence length steps — not just the build-time one
            seq_chunk = next((c for c in (512, 256, 128)
                              if s % c == 0), s)
            nc = s // seq_chunk
            xc = x.reshape(b, nc, seq_chunk, hid).swapaxes(0, 1)
            tc = targets.reshape(b, nc, seq_chunk).swapaxes(0, 1)

            @jax.checkpoint
            def chunk_nll(x_chunk, t_chunk):
                logits = jnp.dot(x_chunk, wt).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                return jnp.sum(-jnp.take_along_axis(
                    logp, t_chunk[..., None], axis=-1)[..., 0])

            def body(acc, ct):
                return acc + chunk_nll(*ct), None

            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                    (xc, tc))
            return total / (b * s)

        loss, head_vjp = jax.vjp(
            head_loss, state.norm_params, head_param, h)
        d_norm, d_head, dh = head_vjp(jnp.ones((), jnp.float32))

        new_norm, new_norm_opt = _apply_update(
            state.norm_params, d_norm, state.norm_opt)
        new_head = state.head
        new_head_opt = state.head_opt
        embed_grad_from_head = None
        if state.head is None:
            embed_grad_from_head = d_head   # tied: fold into embed grad
        else:
            new_head, new_head_opt = _apply_update(
                state.head, d_head, state.head_opt)

        # ---- backward: reverse loop, update-in-place per layer -------
        def bwd_body(j, carry):
            dh, params, opt = carry
            i = L - 1 - j
            h_in = jax.lax.dynamic_index_in_dim(stash, i, 0,
                                                keepdims=False)
            p_i = _tree_index(params, i)

            def f(p, x):
                return block.apply({"params": p}, x, positions)

            _, vjp_fn = jax.vjp(f, p_i, h_in)
            dp_i, dh_in = vjp_fn(dh)
            new_p_i, new_opt_i = _apply_update(
                p_i, dp_i, _tree_index(opt, i))
            return (dh_in, _tree_update(params, new_p_i, i),
                    _tree_update(opt, new_opt_i, i))

        dh0, new_block, new_block_opt = jax.lax.fori_loop(
            0, L, bwd_body, (dh, state.block_params, state.block_opt))

        # ---- embedding backward (scatter-add of dh0) -----------------
        def embed_fwd(e):
            return embed_lookup(e, tokens, cfg)

        _, embed_vjp = jax.vjp(embed_fwd, state.embed)
        (d_embed,) = embed_vjp(dh0)
        if embed_grad_from_head is not None:
            d_embed = d_embed + embed_grad_from_head.astype(d_embed.dtype)
        new_embed, new_embed_opt = _apply_update(
            state.embed, d_embed, state.embed_opt)

        new_state = StreamingState(
            step=state.step + 1,
            block_params=new_block,
            embed=new_embed,
            head=new_head,
            norm_params=new_norm,
            block_opt=new_block_opt,
            embed_opt=new_embed_opt,
            head_opt=new_head_opt,
            norm_opt=new_norm_opt,
        )
        return new_state, {"loss": loss}

    from dlrover_tpu.parallel.mesh import MeshSpec, create_mesh

    return StreamingTrainer(
        config=cfg,
        init_fn=jax.jit(_init),
        step_fn=jax.jit(_step, donate_argnums=(0,)),
        micro_batch=micro_batch,
        seq_len=seq_len,
        mesh=create_mesh(
            MeshSpec(),
            (devices if devices is not None else jax.devices())[:1]),
    )
