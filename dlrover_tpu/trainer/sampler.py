"""Checkpointable elastic distributed sampler.

Capability parity: dlrover/trainer/torch/elastic/sampler.py:25-130
(ElasticDistributedSampler: rank-partitioned indices, `state_dict` records
completed samples, `load_state_dict` resumes mid-epoch even when the world
size changed between save and restore).
"""

from __future__ import annotations

import random
from typing import Dict, Iterator, List


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        # samples already consumed (across all replicas) in this epoch
        self.completed_num = 0

    # -- iteration ---------------------------------------------------------
    def _epoch_indices(self) -> List[int]:
        indices = list(range(self.dataset_size))
        if self.shuffle:
            rng = random.Random(self.seed + self.epoch)
            rng.shuffle(indices)
        return indices

    def __iter__(self) -> Iterator[int]:
        indices = self._epoch_indices()[self.completed_num:]
        if self.drop_last:
            usable = (len(indices) // self.num_replicas) * self.num_replicas
            indices = indices[:usable]
        # round-robin partition so a world resize only re-deals future
        # samples (reference: sampler.py:71-116)
        yield from indices[self.rank::self.num_replicas]

    def __len__(self) -> int:
        remaining = self.dataset_size - self.completed_num
        if self.drop_last:
            return remaining // self.num_replicas
        return (remaining + self.num_replicas - 1 - self.rank
                ) // self.num_replicas

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.completed_num = 0

    def record_batch(self, global_batch_size: int) -> None:
        """Advance the consumed-sample cursor by one *global* batch."""
        self.completed_num += global_batch_size

    # -- checkpoint --------------------------------------------------------
    def state_dict(self) -> Dict[str, int]:
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num,
            "seed": self.seed,
        }

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.epoch = int(state.get("epoch", 0))
        self.seed = int(state.get("seed", self.seed))
        completed = int(state.get("completed_num", 0))
        # a resized world may not divide the old position evenly; clamp to a
        # replica boundary so every rank resumes at the same cursor
        completed -= completed % self.num_replicas
        self.completed_num = min(completed, self.dataset_size)
