"""Step-hang watchdog: self-abort a silently stuck worker.

A collective that deadlocks after a peer dies (or a wedged host-callback,
or an input pipeline stuck on a dead filesystem) hangs the step loop
*forever* — the worker stays alive, heartbeats keep flowing, and nothing
above notices for `hang_seconds` (default 30 min) of master-side
timeout. This watchdog is the worker-side backstop: a thread that
notices no step progress past ``Context.hang_watchdog_s``, dumps
every thread's stack plus the flight record (the postmortem that tells
*where* it hung), and self-aborts with SIGABRT so the agent's normal
exit path restarts the worker. The agent classifies the abort as
``NodeExitReason.HANG`` — distinct from a crash (no relaunch-budget
charge) and from a drain.

stdlib + obs only: the agent's trivial test workers (and the chaos
harness) import this without pulling jax.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger


def default_warmup_s(hang_s: float) -> float:
    """First-step budget: the first step may legitimately take much
    longer than steady state (inline compile when AOT precompile
    missed). Shared with the agent's RelaunchGovernor, whose
    no-progress horizon reasons about when an incarnation watched by
    THIS formula must have stepped — keep them in lockstep."""
    return max(2.0 * hang_s, 300.0)


def all_thread_stacks() -> Dict[str, list]:
    """Formatted stacks of every live thread, keyed by thread name —
    the "where is it stuck" evidence a hang postmortem needs."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for tid, frame in sys._current_frames().items():
        name = names.get(tid, f"thread-{tid}")
        stacks[name] = [line.rstrip("\n")
                        for line in traceback.format_stack(frame)]
    return stacks


def _default_abort() -> None:
    # SIGABRT (not SIGKILL): a distinct, classifiable exit the agent
    # maps to NodeExitReason.HANG, and the default disposition still
    # guarantees death even with exotic signal setups
    os.kill(os.getpid(), signal.SIGABRT)


class StepHangWatchdog:
    """Arm with ``start()``, feed with ``notify_step(step)`` once per
    loop iteration, disarm with ``stop()`` before long non-step phases
    (final checkpoint wait). ``clock``/``abort_fn`` are injectable for
    deterministic tests (fake time, no real abort)."""

    def __init__(self, hang_s: float,
                 poll_s: Optional[float] = None,
                 warmup_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 abort_fn: Callable[[], None] = _default_abort):
        self._hang_s = hang_s
        self._poll_s = (poll_s if poll_s is not None
                        else max(1.0, min(hang_s / 4.0, 30.0)))
        self._warmup_s = (warmup_s if warmup_s is not None
                          else default_warmup_s(hang_s))
        self._clock = clock
        self._abort_fn = abort_fn
        self._lock = threading.Lock()
        self._last_progress = clock()
        self._last_step = -1
        self._fired = False
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- step-loop side ----------------------------------------------------
    def notify_step(self, step: int) -> None:
        with self._lock:
            self._last_step = step
            self._last_progress = self._clock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Arm (or RE-arm after ``stop()`` — a driver that calls
        ``run()`` repeatedly on one loop instance must stay protected
        on every run, not just the first)."""
        if self._hang_s <= 0 or self._fired:
            return
        if (self._thread is not None and self._thread.is_alive()
                and not self._stopped.is_set()):
            return                       # already armed
        with self._lock:
            self._last_progress = self._clock()
            # a fresh arm gets the warmup budget again: the new run's
            # first step may re-lower/compile just like the first ever
            self._last_step = -1
        # a NEW event per arm: the previous (stopped) thread holds the
        # old set event and winds down on its next poll tick, even
        # though the new thread is already watching
        self._stopped = threading.Event()
        stopped = self._stopped

        def _loop():
            while not stopped.wait(self._poll_s):
                if self.check_once():
                    return

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="step-hang-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    # -- the check (public for fake-clock tests) --------------------------
    def check_once(self) -> bool:
        """Returns True when the hang fired (the loop then exits; in
        production ``abort_fn`` has already killed the process)."""
        with self._lock:
            if self._fired:
                return True
            budget = (self._hang_s if self._last_step >= 0
                      else self._warmup_s)
            stalled = self._clock() - self._last_progress
            if stalled <= budget:
                return False
            self._fired = True
            step, last = self._last_step, stalled
        self._fire(step, last)
        return True

    def _fire(self, step: int, stalled_s: float) -> None:
        stacks = all_thread_stacks()
        logger.error(
            "step-hang watchdog: no progress for %.0fs (last step %d); "
            "dumping stacks and aborting", stalled_s, step)
        recorder = obs.get_flight_recorder()
        recorder.record_event("step_hang", step=step,
                              stalled_s=round(stalled_s, 1),
                              hang_watchdog_s=self._hang_s,
                              stacks=stacks)
        obs.get_registry().counter(
            "dlrover_tpu_step_hang_aborts_total",
            "Workers self-aborted by the step-hang watchdog").inc()
        recorder.dump(reason="step-hang")
        self._abort_fn()
