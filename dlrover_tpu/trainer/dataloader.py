"""Elastic data loading: master-tuned batch size + dynamic shard feed.

Capability parity:
- ElasticDataLoader hot-reloading batch size from the tuned-config file
  (dlrover/trainer/torch/elastic/dataloader.py:26,97-141, written by
  ParalConfigTuner elastic_agent/config/paral_config_tuner.py:55-60).
- ShardingClient-driven datasets: workers fetch index shards from the
  master instead of statically partitioning
  (elastic_agent/sharding/client.py:192 fetch_shard).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler


class ElasticDataLoader:
    """Batch iterator over an indexable dataset with a checkpointable
    sampler and a hot-reloadable batch size."""

    def __init__(
        self,
        dataset,                       # indexable: dataset[i] -> np record
        batch_size: int,
        sampler: Optional[ElasticDistributedSampler] = None,
        collate_fn: Optional[Callable] = None,
        config_file: Optional[str] = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or ElasticDistributedSampler(
            len(dataset), shuffle=False
        )
        self.collate_fn = collate_fn or _default_collate
        self._config_file = config_file
        self._config_version = -1
        self.load_config()

    def load_config(self) -> None:
        """Pick up a master-tuned batch size if the config file changed."""
        if not self._config_file or not os.path.exists(self._config_file):
            return
        try:
            with open(self._config_file) as f:
                config = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        version = config.get("version", 0)
        if version <= self._config_version:
            return
        self._config_version = version
        new_bs = config.get("dataloader_batch_size", 0)
        if new_bs > 0 and new_bs != self.batch_size:
            logger.info("hot-reloaded batch size %d -> %d (config v%d)",
                        self.batch_size, new_bs, version)
            self.batch_size = new_bs

    def __iter__(self) -> Iterator:
        batch: List = []
        for index in self.sampler:
            batch.append(self.dataset[index])
            if len(batch) >= self.batch_size:
                yield self.collate_fn(batch)
                batch = []
                self.load_config()
        if batch:
            yield self.collate_fn(batch)


class ShardedDataset:
    """Iterates master-dispatched shards of a dataset (dynamic sharding);
    faster workers pull more shards (reference: IndexShardingClient,
    sharding/client.py:233)."""

    def __init__(self, master_client, dataset_name: str, dataset,
                 batch_size: int, collate_fn: Optional[Callable] = None,
                 wait_poll_s: float = 0.2):
        self._client = master_client
        self.dataset_name = dataset_name
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self._wait_poll_s = wait_poll_s
        self._current_task_id: Optional[int] = None

    def register(self, shard_size: int, num_epochs: int = 1,
                 shuffle: bool = False, storage_type: str = "text") -> None:
        from dlrover_tpu.common.messages import DatasetShardParams

        self._client.report_dataset_shard_params(DatasetShardParams(
            dataset_name=self.dataset_name,
            dataset_size=len(self.dataset),
            shard_size=shard_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            task_type=TaskType.TRAINING,
            storage_type=storage_type,
        ))

    def __iter__(self) -> Iterator:
        while True:
            task = self._client.get_task(self.dataset_name)
            if task.task_type == TaskType.WAIT:
                time.sleep(self._wait_poll_s)
                continue
            if task.is_empty or task.task_type == TaskType.NONE:
                return
            self._current_task_id = task.task_id
            shard = task.shard
            indices = (shard.indices if shard.indices is not None
                       else range(shard.start, shard.end))
            batch: List = []
            for index in indices:
                batch.append(self.dataset[index])
                if len(batch) >= self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch:
                yield self.collate_fn(batch)
            self._client.report_task_result(self.dataset_name, task.task_id,
                                            success=True)
            self._current_task_id = None


def _default_collate(batch: Sequence) -> np.ndarray:
    return np.stack([np.asarray(item) for item in batch])
