"""ElasticTrainLoop: the user-facing elastic training driver.

Capability parity: `ElasticTrainer` (dlrover/trainer/torch/elastic/
trainer.py:225 — fixed-global-batch grad accumulation as the world resizes,
step reporting, the checkpoint hook the reference left unimplemented
:295-319) — TPU re-design:

- The loop OWNS re-lowering: it builds the mesh from the live device set,
  picks (accum, micro) to hold the global batch fixed via
  `choose_accumulation`, and jits the train step once per world shape.
- Flash checkpoint at intervals + forced save on SIGTERM (the agent sends
  SIGTERM before a membership-change restart, elastic_agent.py), so an
  elastic resize resumes from the last committed step with data position.
- Global-step reports feed the master SpeedMonitor (parity:
  TorchTrainingMonitor elastic_agent/monitor/training.py:78).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import numpy as np

from dlrover_tpu import obs
from dlrover_tpu.agent.preemption import DrainRequestSource
from dlrover_tpu.checkpoint import FlashCheckpointer
from dlrover_tpu.common.constants import WorkerExit
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import MeshSpec, create_mesh, dp_size
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler
from dlrover_tpu.trainer.train_step import (
    build_trainer,
    choose_accumulation,
)
from dlrover_tpu.trainer.watchdog import StepHangWatchdog


class DrainExit(SystemExit):
    """Clean graceful drain: the loop consumed a preemption drain
    request, ran the emergency checkpoint, and the process must exit
    with the clean-drain code the agent classifies as NON-failure."""

    def __init__(self, reason: str = ""):
        super().__init__(WorkerExit.DRAIN)
        self.reason = reason


@dataclasses.dataclass
class TrainLoopConfig:
    global_batch: int
    seq_len: int
    max_micro_per_replica: int = 8
    max_steps: int = 0                    # 0 = until data exhausted
    checkpoint_dir: str = ""
    save_interval_steps: int = 100
    # 8/4 = groupwise int-quantized state payloads (~4x fewer restore
    # bytes; see checkpoint/quantized.py); 0 = exact dtypes
    checkpoint_quantize_bits: int = dataclasses.field(
        default_factory=lambda: int(os.environ.get(
            "DLROVER_TPU_CKPT_QUANT_BITS", "0")))
    report_interval_steps: int = 10
    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    rules: Optional[Any] = None
    # jax.profiler trace window (reference tracing parity, SURVEY §5a):
    # a perfetto/xplane trace of steps [start, start+num) is written to
    # profile_dir (defaults to $DLROVER_TPU_PROFILE_DIR)
    profile_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "DLROVER_TPU_PROFILE_DIR", ""))
    profile_start_step: int = 3           # skip compile steps
    profile_num_steps: int = 3
    # AOT-compile the train step concurrently with the checkpoint read
    # (restore pays max(read, compile) instead of their sum)
    overlap_restore_compile: bool = True


class ElasticTrainLoop:
    def __init__(
        self,
        model,
        tx,
        loss_fn: Callable,
        config: TrainLoopConfig,
        master_client=None,
        devices=None,
        trainer=None,
    ):
        """`trainer` overrides the built dense trainer with any object
        exposing the ShardedTrainer surface (init/abstract_state/step/
        shard_batch/accum_steps/micro_batch) — e.g. a PipelinedTrainer,
        making pipeline training elastic with checkpoint-resume."""
        self.config = config
        self.client = master_client
        # finished spans batched for the master (flushed at report
        # intervals); registered before any span below so the recompile
        # span of THIS (re)build is part of the shipped timeline. A
        # failed construction must deregister (the global sink list
        # outlives this instance).
        self._span_exporter = obs.SpanExporter()
        obs.add_span_sink(self._span_exporter)
        try:
            self._init_inner(model, tx, loss_fn, config, devices, trainer)
        except BaseException:
            obs.remove_span_sink(self._span_exporter)
            raise

    def _init_inner(self, model, tx, loss_fn, config, devices,
                    trainer) -> None:
        from dlrover_tpu.common.constants import NodeEnv

        # multi-slice hierarchical DP: this worker's slice identity.
        # With a slice id and a master, the gradient sync is two-level —
        # the jitted step returns the in-slice mean (split grad/apply)
        # and the cross-slice mean is exchanged host-side over DCN
        # (parallel/dcn_sync.py), tolerating an absent slice.
        self._slice_id = int(os.environ.get(NodeEnv.SLICE_ID, "-1"))
        slice_mode = self._slice_id >= 0 and self.client is not None
        # the host-level sync moves full gradient/state values through
        # host memory (np.asarray) — only valid when this process can
        # address every shard, i.e. a single-process slice world.
        # Multi-host slices use the single-program hierarchical path
        # instead (MeshSpec.dcn + the in-program dcn-axis reduce).
        slice_world = int(os.environ.get(NodeEnv.WORLD_SIZE, "1"))
        if slice_mode and slice_world > 1:
            logger.warning(
                "slice %d spans %d processes: the host-level DCN "
                "gradient sync needs a single-process slice world — "
                "disabling it (use the in-program hierarchical mesh, "
                "MeshSpec.dcn, for multi-host slices)",
                self._slice_id, slice_world)
            slice_mode = False
        # online parallelism re-plan (parallel/planner.py): the master's
        # deterministic mesh + batch shape for THIS world. Applied
        # before the mesh is built; any failure is LOUD
        # (replan_fallback flight event) and falls back to the
        # configured shape — the checkpoint-restart path of old.
        self._shard_plan: Optional[Dict[str, Any]] = None
        self._plan_mesh_spec: Optional[MeshSpec] = None
        self._replan_applied = ""       # "" | "batch" | "mesh+batch"
        # True when the applied plan's execution shape differs from
        # what the PREVIOUS incarnation ran (sidecar signature): only
        # then is this rebuild a RESIZE worth pricing — a plain
        # relaunch re-applying the unchanged plan must not mint
        # replan_* spans the goodput tools read as "a resize happened"
        self._replan_changed = False
        self.global_batch = config.global_batch
        self._trim_batch = 0
        # device-truth HBM peak watermark (obs/device.py): one
        # memory_stats read per local device per step, CPU-safe no-op
        # after one probe; the report-window peak rides the step report
        # so HbmPressureRule judges the IN-step transient, not the
        # between-steps trough the monitor tick samples. Built BEFORE
        # the trainer so every (re)build can mark a program-episode
        # boundary (note_recompile).
        self.device_telemetry = obs.DeviceTelemetry()
        if trainer is not None:
            self.trainer = trainer
            self.mesh = trainer.mesh
            self.dp = dp_size(self.mesh)
            self.accum = trainer.accum_steps
            self.micro_global = trainer.micro_batch
            # custom trainers (pipeline) own their step: no split path
            slice_mode = slice_mode and trainer.grad_fn is not None
        else:
            self._resolve_shard_plan(config, devices)
            try:
                self._build_dense_trainer(model, tx, loss_fn, config,
                                          devices, slice_mode)
            except Exception as e:  # noqa: BLE001 — a plan mesh the
                # MODEL cannot shard over (an axis size not dividing a
                # model dim the planner cannot see) must fall back to
                # the configured shape, loudly — never a crash-looping
                # worker
                if self._plan_mesh_spec is None:
                    raise
                self._replan_fallback(
                    self._shard_plan,
                    f"planned mesh rejected by the model/trainer: {e}")
                self._build_dense_trainer(model, tx, loss_fn, config,
                                          devices, slice_mode)
        self._slice_sync = None
        if slice_mode:
            from dlrover_tpu.parallel.dcn_sync import SliceGradSync

            # the slice's process 0 posts payloads; every rank collects
            is_leader = int(os.environ.get(NodeEnv.PROCESS_ID,
                                           "0")) == 0
            self._slice_sync = SliceGradSync(
                self.client, self._slice_id, is_leader=is_leader,
                abort_fn=lambda: self._stop_requested.is_set())
            logger.info("slice-scoped hierarchical DP armed: slice=%d "
                        "leader=%s", self._slice_id, is_leader)
        self.checkpointer = (
            FlashCheckpointer(config.checkpoint_dir,
                              config.save_interval_steps,
                              quantize_bits=config.checkpoint_quantize_bits)
            if config.checkpoint_dir else None
        )
        self._stop_requested = threading.Event()
        self.last_restore_timings: Dict[str, float] = {}
        # where the last restore's state came from: "peer" (surviving
        # hosts' staged memory), "mixed" (peer + shard-wise Orbax),
        # "orbax" (storage), "init" (fresh)
        self.last_restore_source = ""
        # peer-to-peer restore (checkpoint/peer_restore.py): the staging
        # store mirrors the live state host-side at every checkpoint
        # boundary; the restorer turns a master restore plan into a
        # shard transfer from surviving donors
        from dlrover_tpu.checkpoint.peer_restore import (
            PeerRestorer,
            PeerStateStore,
        )

        self._peer_store = (PeerStateStore.from_env()
                            if self.checkpointer is not None else None)
        self._peer_restorer = (
            PeerRestorer.from_env(client=self.client)
            if self.checkpointer is not None else None)
        if self._peer_restorer is not None and self._replan_changed:
            # re-plan migration: restore plans stripe each shard's byte
            # ranges across every same-step holder (the resharding
            # transfer primitive, checkpoint/peer_restore.py)
            self._peer_restorer.stripe = True
        self._chaos = None  # built lazily: env may be set post-init
        self._prev_sigterm = None
        # per-step phase attribution (data-wait / h2d / compute /
        # checkpoint), exported beside the metrics file for the agent +
        # tools/diagnose.py; the windowed means ride on step reports as
        # the master's straggler / data-bound evidence
        from dlrover_tpu.common.constants import NodeEnv

        self.timeline = obs.StepTimeline(
            role="worker",
            rank=int(os.environ.get(NodeEnv.NODE_RANK, "-1")))
        self._timeline_path = os.environ.get(NodeEnv.TIMELINE_FILE, "")
        self._timeline_exported_at = 0.0
        # data-pipeline auto-tune (data/prefetch.py): fed the timeline's
        # windowed data_wait fraction at each progress report; the input
        # pipeline consumes `prefetch_tuner.depth_fn` (and its ring
        # recommendation at rebuild boundaries) to stop starving steps
        from dlrover_tpu.common.config import Context as _TuneCtx

        if _TuneCtx.singleton().prefetch_autotune:
            from dlrover_tpu.data.prefetch import PrefetchAutoTuner

            self.prefetch_tuner = PrefetchAutoTuner()
            obs.get_registry().gauge(
                "dlrover_tpu_prefetch_depth",
                "Auto-tuned device-prefetch depth (data/prefetch.py; "
                "grows while the timeline's data_wait fraction exceeds "
                "the tune threshold, decays when the pipeline is calm)",
            ).set_function(self.prefetch_tuner.depth_fn)
        else:
            self.prefetch_tuner = None
        # per-step critical-path trace (obs/steptrace.py): one compact
        # record per step, clock-aligned against the master and batched
        # over the telemetry channel; the join-time probe anchors the
        # offset before the first step, report-cadence refreshes keep
        # the drift allowance small
        from dlrover_tpu.common.config import Context as _TraceCtx

        _trace_ctx = _TraceCtx.singleton()
        self._clock_sync = obs.ClockSync(
            probe_fn=(self.client.probe_clock
                      if self.client is not None else None))
        self._steptrace = (
            obs.StepTraceRecorder(
                capacity=_trace_ctx.steptrace_ring,
                rank=int(os.environ.get(NodeEnv.NODE_RANK, "-1")),
                slice_id=self._slice_id,
                clock_sync=self._clock_sync)
            if _trace_ctx.steptrace_enabled else None)
        if self._steptrace is not None and self.client is not None:
            self._clock_sync.probe()
        # SliceGradSync's per-reduce marks, stashed by _slice_step for
        # the record built at the step boundary
        self._last_sync_trace: Optional[Dict[str, Any]] = None
        # profiler: static window (config) + on-demand captures the
        # agent requests on behalf of a master `profile:{rank}` action
        self.profiler = obs.ProfilerSession(
            request_path=os.environ.get(NodeEnv.PROFILE_REQUEST_FILE, ""),
            static_dir=config.profile_dir,
            static_start=config.profile_start_step,
            static_num=config.profile_num_steps,
        )
        # preemption drain / urgent-checkpoint requests from the agent,
        # consumed at step boundaries (one os.stat per step when armed)
        self._drain_source = DrainRequestSource()
        # step-hang backstop: no progress past hang_watchdog_s → stack
        # dump + self-abort so the agent restarts this worker (0 = off)
        from dlrover_tpu.common.config import Context

        watchdog_s = Context.singleton().hang_watchdog_s
        self._watchdog = (StepHangWatchdog(watchdog_s)
                          if watchdog_s > 0 else None)
        logger.info(
            "elastic loop: dp=%d accum=%d micro(global)=%d mesh=%s",
            self.dp, self.accum, self.micro_global,
            dict(self.mesh.shape),
        )
        # MFU accounting (obs/mfu.py): FLOPs/token + the mesh's
        # aggregate peak; 0 until _report_model_info derives them
        self._flops_per_token = 0.0
        self._peak_flops_total = 0.0
        self._flops_cross_checked = False
        self._report_model_info(model)

    # -- online parallelism re-planning (parallel/planner.py) --------------
    def _build_dense_trainer(self, model, tx, loss_fn, config, devices,
                             slice_mode) -> None:
        """Mesh + accumulation + jitted programs for the current shape
        (the planned mesh when a shard plan applied, the configured one
        otherwise). The trace is PROBED via ``abstract_state`` before
        returning so an invalid planned mesh fails here — inside the
        caller's fallback — instead of at first restore/step."""
        import contextlib

        import jax.numpy as jnp

        mesh_spec = self._plan_mesh_spec or config.mesh_spec
        self.mesh = create_mesh(mesh_spec, devices)
        self.dp = dp_size(self.mesh)
        if self.global_batch % self.dp:
            # the last line of "any world size": even the fallback
            # (configured) mesh must not crash-loop on a world whose dp
            # does not divide the batch — apply the planner's
            # round-DOWN-to-dp rule locally, loudly (the same
            # deliberate adjustment, never a silent wrong batch)
            adjusted = (self.global_batch // self.dp) * self.dp
            if adjusted <= 0:
                raise ValueError(
                    f"dp size {self.dp} exceeds the global batch "
                    f"{self.global_batch}: no mesh over this world can "
                    f"hold even one sample per replica")
            logger.error(
                "world dp %d does not divide the global batch %d: "
                "DELIBERATELY adjusting it to %d (input batches are "
                "trimmed; the sampler advances by the adjusted size)",
                self.dp, self.global_batch, adjusted)
            obs.get_flight_recorder().record_event(
                "replan_batch_adjusted", dp=self.dp,
                requested=self.global_batch, adjusted=adjusted,
                planned=self._plan_mesh_spec is not None)
            self.global_batch = adjusted
            self._trim_batch = adjusted
        self.accum, self.micro_global = choose_accumulation(
            self.global_batch, self.dp,
            config.max_micro_per_replica,
        )
        sample = jnp.zeros((self.micro_global, config.seq_len),
                           jnp.int32)
        # the re-lower after an elastic resize: trace + shardings +
        # jit wrappers for THIS world shape (XLA compile itself lands
        # in the recompile/aot span, train_step.precompile). Under a
        # re-plan the whole rebuild additionally lands in a
        # `replan_rebuild` span — the "rebuild" leg of the re-plan
        # decomposition (plan → migrate → rebuild) the goodput tools
        # price per resize. The nested relower `recompile` span
        # stays the ledger's compile evidence (no double count).
        # a new program is about to be built: the old one's recurring
        # in-step peak stops being HBM-pressure evidence unless the new
        # program re-reaches it (obs/device.py episode semantics)
        self.device_telemetry.note_recompile()
        rebuild_cm = (
            obs.span("replan_rebuild",
                     {"generation": self._shard_plan.get(
                         "generation", 0),
                      "mesh": dict(self.mesh.shape)})
            if self._replan_applied and self._replan_changed
            else contextlib.nullcontext())
        with rebuild_cm, obs.span(
                "recompile",
                {"phase": "relower",
                 "devices": self.dp,
                 "mesh": dict(self.mesh.shape)}):
            trainer = build_trainer(
                model, tx, self.mesh, sample, loss_fn,
                accum_steps=self.accum, micro_batch=self.micro_global,
                rules=config.rules,
                split_grad_apply=slice_mode,
            )
            if self._plan_mesh_spec is not None:
                import jax

                # cheap shape-only probe: surfaces "axis does not
                # divide dim" sharding rejections NOW (they otherwise
                # raise lazily at the first eval_shape/step)
                trainer.abstract_state(jax.random.PRNGKey(0))
        self.trainer = trainer

    def _resolve_shard_plan(self, config, devices=None) -> None:
        """Fetch + apply the master's parallelism plan for this world.

        The plan decides the mesh spec AND the (possibly deliberately
        adjusted) global batch before anything is traced, so a resize
        to ANY world size re-plans instead of crashing on a
        non-divisor batch. No plan at all (standalone runs, masters
        predating the planner) is silent — that is not a failure; a
        plan that cannot be applied is a LOUD ``replan_fallback``."""
        import json

        from dlrover_tpu.common.config import Context
        from dlrover_tpu.common.constants import NodeEnv

        if not Context.singleton().replan_enabled:
            return
        import time as _time

        t0 = _time.monotonic()
        plan = None
        if self.client is not None:
            try:
                plan = self.client.get_shard_plan() or None
            except Exception:  # noqa: BLE001 — degrade to the file
                logger.warning("shard-plan RPC failed; trying the "
                               "join-result plan file",
                               exc_info=True)
        if plan is None:
            path = os.environ.get(NodeEnv.SHARD_PLAN_FILE, "")
            if path:
                try:
                    with open(path) as f:
                        loaded = json.load(f)
                    if isinstance(loaded, dict) and \
                            loaded.get("mesh"):
                        plan = loaded
                except (OSError, json.JSONDecodeError):
                    pass
        if plan is None:
            return
        try:
            self._apply_shard_plan(plan, config, devices)
        except Exception as e:  # noqa: BLE001 — the fallback path
            # must always be reachable: a broken plan falls back to
            # the configured shape, loudly, never a wedged worker
            self._replan_fallback(plan,
                                  f"plan application failed: {e}")
        if self._replan_changed:
            # the "plan" leg of the per-resize pricing — recorded only
            # when this rebuild IS a resize (see _replan_changed)
            obs.record_span(
                "replan_plan", _time.monotonic() - t0,
                attrs={"applied": self._replan_applied,
                       "generation": plan.get("generation", 0),
                       "epoch": plan.get("epoch", 0)})

    def _applied_plan_signature(self, plan: Dict[str, Any],
                                batch: int) -> str:
        """The execution shape this incarnation will run, as a stable
        string (mesh + effective batch + device count)."""
        import json

        return json.dumps({"mesh": plan.get("mesh"),
                           "global_batch": batch,
                           "total_devices": plan.get("total_devices"),
                           "applied": self._replan_applied},
                          sort_keys=True)

    def _note_replan_changed(self, plan: Dict[str, Any],
                             batch: int) -> None:
        """Decide whether this application is a RESIZE (shape differs
        from the previous incarnation's, remembered in a sidecar next
        to the agent-published plan file) or a plain relaunch
        re-applying the same plan. No sidecar path (RPC-only runs) →
        no memory → treated as changed. The sidecar is only READ
        here — it is written once the migration actually completes
        (``_commit_applied_plan``), so a worker that dies mid-resize
        re-runs (and re-prices) the resize on respawn instead of being
        misread as a plain relaunch."""
        from dlrover_tpu.common.constants import NodeEnv

        self._pending_plan_signature = self._applied_plan_signature(
            plan, batch)
        path = os.environ.get(NodeEnv.SHARD_PLAN_FILE, "")
        if not path:
            self._replan_changed = True
            return
        previous = None
        try:
            with open(f"{path}.applied") as f:
                previous = f.read()
        except OSError:
            pass
        self._replan_changed = previous != self._pending_plan_signature

    def _commit_applied_plan(self) -> None:
        """The resize completed (state restored/migrated under the new
        shape): remember the applied signature so the NEXT incarnation
        can tell a plain relaunch from a resize."""
        signature = getattr(self, "_pending_plan_signature", None)
        if not signature:
            return
        from dlrover_tpu.common.constants import NodeEnv

        path = os.environ.get(NodeEnv.SHARD_PLAN_FILE, "")
        if not path:
            return
        try:
            tmp = f"{path}.applied.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(signature)
            os.replace(tmp, f"{path}.applied")
        except OSError:
            pass

    def _apply_shard_plan(self, plan: Dict[str, Any], config,
                          devices=None) -> None:
        import math

        import jax

        from dlrover_tpu.parallel import planner

        # base sanity (feasibility, mesh factors the planned devices,
        # positive batch) is the shared helper's job; the device-count
        # comparison is layered below because slice mode and the
        # independent-replica harness legitimately build less than the
        # plan's global device count
        error = planner.validate_plan(plan, n_devices=0)
        if error is not None:
            self._replan_fallback(plan, error)
            return
        # slice mode builds the per-slice portion (dcn=1): each slice
        # is its own jax program, the dcn axis lives in the host-level
        # cross-slice sync (parallel/dcn_sync.py)
        mesh_dict = (planner.slice_mesh(plan) if self._slice_id >= 0
                     else dict(plan.get("mesh", {})))
        mesh_total = math.prod(int(mesh_dict.get(k, 1)) for k in
                               ("dcn", "data", "fsdp", "tensor", "pipe"))
        n_devices = (len(devices) if devices is not None
                     else jax.device_count())
        world_size = max(1, int(plan.get("world_size", 1) or 1))
        apply_mesh = mesh_total == n_devices
        if not apply_mesh:
            # the CPU multi-process harness runs each rank as an
            # independent full replica (no cross-process SPMD): the
            # global mesh cannot be built locally, but the BATCH plan —
            # the part a divisor-unfriendly resize actually needs —
            # still applies. Anything else is a real mismatch.
            replica_mode = (jax.process_count() == 1
                            and world_size > 1
                            and mesh_total == n_devices * world_size)
            if not replica_mode:
                self._replan_fallback(
                    plan, f"plan mesh covers {mesh_total} device(s); "
                          f"this process sees {n_devices}")
                return
        # the batch contract: honor the planned batch when the plan was
        # computed for the batch this loop was configured with; a plan
        # from a stale profile adjusts LOCALLY by the same
        # round-down-to-dp rule (deliberate either way, never silent)
        planned_batch = int(plan.get("global_batch", 0) or 0)
        requested = int(plan.get("requested_global_batch", 0) or 0)
        if requested != config.global_batch \
                or planned_batch > config.global_batch:
            dp = int(plan.get("dp", 0) or 0) or 1
            planned_batch, _ = planner.adjust_global_batch(
                config.global_batch, dp)
            if planned_batch <= 0:
                self._replan_fallback(
                    plan, f"planned dp {dp} exceeds the configured "
                          f"global batch {config.global_batch}")
                return
        if apply_mesh:
            self._plan_mesh_spec = MeshSpec(
                data=int(mesh_dict.get("data", 1)),
                fsdp=int(mesh_dict.get("fsdp", 1)),
                tensor=int(mesh_dict.get("tensor", 1)),
                pipe=int(mesh_dict.get("pipe", 1)),
                dcn=int(mesh_dict.get("dcn", 1)),
            )
        self.global_batch = planned_batch
        self._trim_batch = (planned_batch
                            if planned_batch < config.global_batch
                            else 0)
        self._shard_plan = plan
        self._replan_applied = "mesh+batch" if apply_mesh else "batch"
        self._note_replan_changed(plan, planned_batch)
        obs.get_flight_recorder().record_event(
            "replan_applied",
            applied=self._replan_applied,
            changed=self._replan_changed,
            mesh=mesh_dict,
            global_batch=planned_batch,
            requested_global_batch=config.global_batch,
            batch_adjusted=planned_batch != config.global_batch,
            resharded=bool(plan.get("resharded")),
            generation=plan.get("generation", 0),
            epoch=plan.get("epoch", 0),
            world_size=world_size)
        obs.get_registry().counter(
            "dlrover_tpu_replan_applied_total",
            "Parallelism plans applied at worker (re)build",
            labelnames=("applied",),
        ).labels(applied=self._replan_applied).inc()
        if planned_batch != config.global_batch:
            logger.warning(
                "re-plan DELIBERATELY adjusted the global batch "
                "%d -> %d (dp %s does not divide it); input batches "
                "are trimmed, the sampler advances by the adjusted "
                "size", config.global_batch, planned_batch,
                plan.get("dp"))
        logger.info(
            "shard plan applied (%s): mesh=%s batch=%d generation=%s "
            "epoch=%s", self._replan_applied, mesh_dict, planned_batch,
            plan.get("generation"), plan.get("epoch"))

    def _replan_fallback(self, plan: Optional[Dict[str, Any]],
                         reason: str) -> None:
        """The hard fallback: today's checkpoint-restart path (the
        configured mesh + Orbax/peer restore at the configured batch).
        Loud by contract — a planner or plan-application failure must
        be visible in the flight dump, never a silently wrong shape."""
        self._shard_plan = None
        self._plan_mesh_spec = None
        self._replan_applied = ""
        self._replan_changed = False
        self.global_batch = self.config.global_batch
        self._trim_batch = 0
        obs.get_flight_recorder().record_event(
            "replan_fallback", reason=reason[:256],
            generation=(plan or {}).get("generation", 0),
            epoch=(plan or {}).get("epoch", 0),
            mesh=(plan or {}).get("mesh"))
        obs.get_registry().counter(
            "dlrover_tpu_replan_fallbacks_total",
            "Re-plans abandoned for the configured-shape "
            "checkpoint-restart path").inc()
        logger.error(
            "parallelism re-plan falling back to the configured shape: "
            "%s (the checkpoint-restart path still applies)", reason)

    def _report_model_info(self, model=None) -> None:
        """One-shot static stats to the master's resource optimizer
        (reference: profile_extractor → ModelInfo) plus the FLOPs model
        behind every MFU number (obs/mfu.py): analytic 6·params with
        the causal attention term when the model config exposes its
        shape, cross-checked later against the compiled step's XLA cost
        analysis (_maybe_cross_check_flops)."""
        try:
            abstract = self.trainer.abstract_state(jax.random.PRNGKey(0))
            leaves = jax.tree.leaves(abstract.params)
            param_count = sum(int(np.prod(l.shape)) for l in leaves)
            param_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves)
            tokens_per_step = self.global_batch * self.config.seq_len
            cfg = getattr(model, "config", None)
            self._param_count = param_count
            self._param_bytes = param_bytes
            # same accounting as bench.py: a gather-lookup embedding
            # table with an untied head does no matmul — crediting it
            # would report a higher MFU than the bench measures for the
            # identical model
            uncounted = 0.0
            if (getattr(cfg, "embed_impl", "") == "gather"
                    and not getattr(cfg, "tie_embeddings", True)):
                uncounted = (getattr(cfg, "vocab_size", 0)
                             * getattr(cfg, "hidden_size", 0))
            self._flops_per_token = obs.mfu.flops_per_token(
                param_count,
                num_layers=getattr(cfg, "num_layers", 0),
                hidden_size=getattr(cfg, "hidden_size", 0),
                seq_len=self.config.seq_len,
                uncounted_embed_params=uncounted,
            )
            device = jax.devices()[0]
            peak_chip = obs.mfu.peak_flops_per_chip(
                getattr(device, "device_kind", ""),
                backend=jax.default_backend())
            chips = jax.device_count()
            self._peak_flops_total = peak_chip * max(1, chips)
            if self.client is None:
                return
            # dim-divisibility granules for the planner: a tensor way
            # must divide every tensor-sharded dim (heads/kv/mlp/
            # vocab), an fsdp way the embed dim — gcd'ed so the master
            # can filter candidates it cannot trace-probe itself
            import math as _math

            tensor_dims = [int(getattr(cfg, k, 0) or 0)
                           for k in ("num_heads", "n_head",
                                     "num_kv_heads",
                                     "intermediate_size", "vocab_size")]
            tensor_dims = [d for d in tensor_dims if d > 0]
            tensor_divisor = (_math.gcd(*tensor_dims)
                              if tensor_dims else 0)
            fsdp_divisor = int(getattr(cfg, "hidden_size", 0)
                               or getattr(cfg, "n_embd", 0) or 0)
            # batch_size = the CONFIGURED batch (the planner's
            # requested baseline — reporting the adjusted one would
            # ratchet the profile down after every adjusting resize);
            # effective_global_batch = what this incarnation actually
            # trains (the tokens/s denominator)
            self.client.report_model_info(
                param_count=param_count, param_bytes=param_bytes,
                flops_per_step=self._flops_per_token * tokens_per_step,
                batch_size=self.config.global_batch,
                seq_len=self.config.seq_len,
                flops_per_token=self._flops_per_token,
                peak_flops_per_chip=peak_chip,
                chips=chips,
                flops_source="analytic",
                tensor_divisor=tensor_divisor,
                fsdp_divisor=fsdp_divisor,
                effective_global_batch=self.global_batch,
            )
        except Exception:   # noqa: BLE001 — stats are advisory
            logger.warning("model-info report failed", exc_info=True)

    def _maybe_cross_check_flops(self) -> None:
        """Once, after the step is AOT-compiled: cross-check the
        analytic FLOPs/token against XLA's cost analysis of the actual
        program. On a >2x divergence (an exotic model the 6·params
        formula misjudges) the measured value is adopted and
        re-reported, so MFU gauges track what the hardware really
        executes."""
        if self._flops_cross_checked:
            return
        compiled = getattr(self.trainer, "_compiled_step", None)
        if compiled is None:
            return
        self._flops_cross_checked = True
        # one compile event per AOT build: wall time + the compiled
        # step's cost-analysis FLOPs/bytes into the flight record and
        # gauges (obs/device.py) — the device truth behind the MFU
        # cross-check below and the calibration table's predictions
        try:
            timings = getattr(self.trainer, "precompile_timings", {})
            obs.device.record_compile_event(
                wall_s=float(timings.get("trace_lower_s", 0.0))
                + float(timings.get("compile_or_cache_load_s", 0.0)),
                compiled=compiled, kind="aot",
                mesh=dict(self.mesh.shape))
        except Exception:  # noqa: BLE001 — telemetry, never the loop
            logger.warning("compile event record failed", exc_info=True)
        measured = obs.mfu.cost_analysis_flops(compiled)
        tokens_per_step = self.global_batch * self.config.seq_len
        adopted = obs.mfu.cross_check(self._flops_per_token, measured,
                                      tokens_per_step)
        if adopted is None:
            return
        logger.warning(
            "FLOPs model cross-check: analytic %.3e/token vs XLA cost "
            "analysis %.3e/token — adopting the measured value",
            self._flops_per_token, adopted)
        self._flops_per_token = adopted
        if self.client is not None:
            try:
                device = jax.devices()[0]
                self.client.report_model_info(
                    param_count=getattr(self, "_param_count", 0),
                    param_bytes=getattr(self, "_param_bytes", 0),
                    flops_per_step=adopted * tokens_per_step,
                    batch_size=self.config.global_batch,
                    effective_global_batch=self.global_batch,
                    seq_len=self.config.seq_len,
                    flops_per_token=adopted,
                    peak_flops_per_chip=obs.mfu.peak_flops_per_chip(
                        getattr(device, "device_kind", ""),
                        backend=jax.default_backend()),
                    chips=jax.device_count(),
                    flops_source="cost_analysis",
                )
            except Exception:  # noqa: BLE001 — stats are advisory
                pass

    # -- signals -----------------------------------------------------------
    def install_signal_handler(self) -> None:
        """SIGTERM (agent restart) → finish the step, force-save, exit."""

        def _handler(signum, frame):
            logger.info("SIGTERM: will checkpoint and stop after this step")
            recorder = obs.get_flight_recorder()
            recorder.record_event("sigterm", pid=os.getpid())
            recorder.dump(reason="sigterm")
            self._stop_requested.set()

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)

    # -- restore -----------------------------------------------------------
    def restore_or_init(self, rng,
                        sampler: Optional[ElasticDistributedSampler] = None
                        ) -> Tuple[Any, int]:
        """Restore the latest checkpoint onto THIS mesh (resharding as
        needed) or initialize fresh. Returns (state, start_step).

        Restore is attempted against an ABSTRACT target (shapes +
        shardings, no allocation) so a resume never holds two full copies
        of params+optimizer state in HBM.

        While the checkpoint bytes stream, the train step is AOT-compiled
        in a background thread (trace + lower + XLA compile / persistent-
        cache load from the abstract state) so a respawned worker pays
        max(read, compile), not read + compile. Per-phase wall-clock lands
        in `self.last_restore_timings`."""
        import time as _time

        timings: Dict[str, float] = {}
        self.last_restore_timings = timings
        with obs.span("restore_or_init") as restore_span:
            t_migrate = _time.monotonic()
            compile_thread = None
            if (self.config.overlap_restore_compile
                    and hasattr(self.trainer, "precompile")):
                compile_thread = threading.Thread(
                    target=self._precompile_quietly, daemon=True)
                t_compile_start = _time.monotonic()
                compile_thread.start()
            if self.checkpointer is None:
                state, step = self.trainer.init(rng), 0
                self.last_restore_source = "init"
            else:
                t0 = _time.monotonic()
                abstract = self.trainer.abstract_state(rng)
                timings["abstract_state_s"] = round(
                    _time.monotonic() - t0, 2)
                source = "orbax"
                restored = None
                if self._peer_restorer is not None:
                    # the peer branch: surviving hosts' staged state
                    # instead of the storage round-trip, overlapped with
                    # the same background compile as the Orbax read
                    peer = None
                    try:
                        peer = self._peer_restorer.restore(
                            abstract, self.checkpointer, timings)
                    except Exception:  # noqa: BLE001 — peers are an
                        # optimization; storage is the ground truth
                        logger.warning("peer restore failed; falling "
                                       "back to Orbax", exc_info=True)
                    if peer is not None:
                        p_state, p_data, p_step, source = peer
                        restored = (p_state, p_data, p_step)
                if restored is None:
                    source = "orbax"
                    t0 = _time.monotonic()
                    restored = self.checkpointer.restore(abstract)
                    timings["orbax_read_s"] = round(
                        _time.monotonic() - t0, 2)
                    # the checkpointer's own per-phase decomposition
                    # (step discovery / metadata / tensor read / decode,
                    # bytes + bandwidth) nests under orbax_read_s
                    for key, value in getattr(self.checkpointer,
                                              "last_restore_phases",
                                              {}).items():
                        timings[f"restore_{key}"] = value
                if restored is None:
                    state, step = self.trainer.init(rng), 0
                    self.last_restore_source = "init"
                else:
                    self.last_restore_source = source
                    if source == "orbax":
                        # peer/mixed count themselves (with the donor
                        # table) inside the restorer
                        obs.get_registry().counter(
                            "dlrover_tpu_restore_source_total",
                            "Elastic restores by state source",
                            labelnames=("source",),
                        ).labels(source="orbax").inc()
                    state, data_state, step = restored
                    # split the read from any deferred host->device
                    # transfer (remote-execution backends materialize
                    # lazily)
                    t0 = _time.monotonic()
                    with obs.span("restore_device_put", {"step": step}):
                        jax.block_until_ready(state)
                    timings["device_ready_s"] = round(
                        _time.monotonic() - t0, 2)
                    # post-restore host sync: data position back into
                    # the sampler + the master's shard checkpoint
                    t0 = _time.monotonic()
                    with obs.span("restore_post_sync", {"step": step}):
                        if sampler is not None and \
                                "sampler" in data_state:
                            sampler.load_state_dict(data_state["sampler"])
                        if self.client is not None and \
                                data_state.get("shards"):
                            try:
                                self.client.report_shard_checkpoint(
                                    data_state["shards"])
                            except Exception:
                                logger.warning(
                                    "could not restore master shard "
                                    "checkpoint")
                    timings["post_sync_s"] = round(
                        _time.monotonic() - t0, 2)
            if self._shard_plan is not None and self._replan_changed:
                # the "migrate" leg of the re-plan decomposition
                # (plan → migrate → rebuild): live state landed under
                # the NEW sharding — from peers when any survive, with
                # the shard-wise Orbax fallback otherwise — WITHOUT a
                # checkpoint round-trip on the happy path. Recorded as
                # its own span (nested evidence for the flight dump /
                # goodput tools; the restore_or_init span remains the
                # ledger's restore bucket). Gated on _replan_changed: a
                # plain relaunch re-applying the unchanged plan is not
                # a resize and must not be priced as one.
                migrate_s = _time.monotonic() - t_migrate
                timings["replan_migrate_s"] = round(migrate_s, 3)
                obs.record_span(
                    "replan_migrate", migrate_s,
                    attrs={"step": step,
                           "source": self.last_restore_source,
                           "bytes": timings.get("peer_bytes", 0.0),
                           "generation": self._shard_plan.get(
                               "generation", 0),
                           "resharded": bool(self._shard_plan.get(
                               "resharded"))})
            if compile_thread is not None:
                t0 = _time.monotonic()
                compile_thread.join()
                timings["compile_wait_after_read_s"] = round(
                    _time.monotonic() - t0, 2)
                timings["compile_total_s"] = round(
                    _time.monotonic() - t_compile_start, 2)
                timings.update(
                    getattr(self.trainer, "precompile_timings", {}))
            restore_span.set_attr("start_step", step)
            restore_span.set_attr("source", self.last_restore_source)
            for key, value in timings.items():
                restore_span.set_attr(key, value)
        if timings:
            logger.info("restore timings: %s", timings)
        if self._slice_sync is not None:
            # a re-formed slice behind the fleet adopts the current
            # state over DCN (restore_source/step above still record
            # what the RESTORE produced — the catch-up is on top)
            state, step = self._maybe_slice_catch_up(state, step,
                                                     sampler)
        # the migration landed: commit the applied-plan signature so a
        # future PLAIN relaunch is not re-priced as a resize (a crash
        # before this point deliberately leaves the old signature — the
        # respawn re-runs the resize)
        self._commit_applied_plan()
        self._flush_telemetry()
        return state, step

    def _precompile_quietly(self) -> None:
        try:
            self.trainer.precompile()
        except Exception:
            # AOT is an optimization: the jitted path compiles on first
            # step regardless
            logger.warning("train-step precompile failed; first step "
                           "will compile inline", exc_info=True)

    # -- main loop ---------------------------------------------------------
    def run(
        self,
        state,
        batches: Iterable[Tuple[np.ndarray, np.ndarray]],
        start_step: int = 0,
        sampler: Optional[ElasticDistributedSampler] = None,
    ) -> Tuple[Any, Dict[str, float]]:
        """Train over (tokens, targets) global batches. Returns the final
        state and last metrics."""
        raw_metrics: Dict[str, Any] = {}
        if self._watchdog is not None:
            self._watchdog.start()
        try:
            return self._run_inner(state, batches, start_step, sampler,
                                   raw_metrics)
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
            # a step failure (the expected failure mode here) must still
            # flush an active profiler trace, or the next loop's
            # start_trace raises on the dangling session
            self.profiler.stop()

    def _run_inner(self, state, batches, start_step, sampler,
                   raw_metrics):
        import time as _time

        config = self.config
        step = start_step
        if self._chaos is None:
            from dlrover_tpu.diagnostics.chaos import ChaosInjector

            self._chaos = ChaosInjector()
        step_hist = obs.get_registry().histogram(
            "dlrover_tpu_worker_step_seconds",
            "Host wall-clock per train-loop iteration (dispatch-bound "
            "unless a host sync lands in the step)")
        batch_iter = iter(batches)
        while True:
            # the step BOUNDARY is where a drain request is consumed:
            # `state` is a complete post-step state here, so the
            # emergency save never snapshots mid-accumulation
            drain = self._drain_source.poll()
            if drain is not None:
                # the deadline-bounded emergency save can legitimately
                # block for minutes of Orbax commit: disarm the watchdog
                # (a save is not a stall), re-arm for save-and-continue
                if self._watchdog is not None:
                    self._watchdog.stop()
                self._consume_drain(drain, step, state, sampler)
                if self._watchdog is not None:
                    self._watchdog.start()
            # data-wait measured explicitly: the time this loop starves
            # on the input pipeline is the diagnosis engine's
            # "pipeline-bound, not a hardware straggler" signal
            t_step = _time.monotonic()
            try:
                tokens, targets = next(batch_iter)
            except StopIteration:
                break
            if self._trim_batch and len(tokens) > self._trim_batch:
                # the re-plan's deliberate batch adjustment: the input
                # pipeline still yields the configured batch; train on
                # the planned (dp-divisible) prefix. Recorded once in
                # the replan_applied event — never a silent truncation.
                tokens = tokens[:self._trim_batch]
                targets = targets[:self._trim_batch]
            t_data = _time.monotonic()
            self.profiler.poll(step - start_step)
            tok, tgt = self.trainer.shard_batch(tokens, targets)
            if self._slice_sync is not None:
                state, raw_metrics = self._slice_step(state, tok, tgt,
                                                      step + 1)
            else:
                state, raw_metrics = self.trainer.step(state, tok, tgt)
            step += 1
            # scripted fault injection (no-op unless DLROVER_TPU_CHAOS)
            self._chaos.maybe_inject(step)
            if sampler is not None:
                # the EFFECTIVE batch (re-plan adjusted when the world
                # does not divide the configured one): the sampler's
                # position advances by what was actually consumed
                sampler.record_batch(self.global_batch)
            t_compute_end = _time.monotonic()
            # from AFTER the batch fetch, as before the timeline landed:
            # this series' meaning (dispatch-bound step time) must not
            # silently absorb data wait — that lives in the timeline and
            # the data_wait_fraction gauge
            step_hist.observe(t_compute_end - t_data)
            ckpt_s = 0.0
            if self.checkpointer is not None:
                forced = self._stop_requested.is_set()
                data_state = self._data_state(sampler)
                saved = self.checkpointer.maybe_save(
                    step, state, data_state, force=forced,
                )
                if saved:
                    # mirror the saved cut into the host-RAM peer
                    # cache: peer step N and Orbax step N are the same
                    # cut, so a shard-wise restore across both sources
                    # stays consistent (with a quantized checkpoint the
                    # peer copy keeps live precision — strictly higher
                    # fidelity than the storage path's dequantized
                    # leaves)
                    self._stage_peer(step, state, data_state)
                ckpt_s = _time.monotonic() - t_compute_end
            if self._watchdog is not None:
                self._watchdog.notify_step(step)
            self.device_telemetry.on_step(step)
            self.timeline.record(
                step, _time.monotonic() - t_step,
                data_wait=t_data - t_step,
                h2d=getattr(self.trainer, "last_shard_batch_s", 0.0),
                compute=getattr(self.trainer, "last_step_dispatch_s",
                                t_compute_end - t_data),
                checkpoint=ckpt_s,
            )
            if self._steptrace is not None:
                self._record_steptrace(step, t_step, t_data,
                                       t_compute_end, ckpt_s)
            if (self.client is not None
                    and step % config.report_interval_steps == 0):
                self._report_progress(step)
                self._flush_telemetry()
            if self._stop_requested.is_set():
                logger.info("stopping at step %d on request", step)
                obs.get_flight_recorder().record_event(
                    "train_stop_requested", step=step)
                break
            if config.max_steps and step - start_step >= config.max_steps:
                break
        # out of the step loop: disarm the watchdog before the final
        # sync/commit waits (a long but legitimate final checkpoint
        # commit is not a step hang)
        if self._watchdog is not None:
            self._watchdog.stop()
        # the device→host sync point: converting metrics blocks on the
        # last step's results (the only host sync the steady-state loop
        # pays — worth a span so slow syncs are visible in postmortems)
        with obs.span("host_sync", {"step": step}):
            metrics = {k: float(v) for k, v in raw_metrics.items()}
        # the step actually REACHED (an early stop — SIGTERM, exhausted
        # data — ends below start_step + max_steps; callers must not
        # assume the request was met)
        metrics["step"] = float(step)
        if self.checkpointer is not None:
            with obs.span("checkpoint_wait"):
                self.checkpointer.wait()
        if self._timeline_path:
            # final flush: runs shorter than a report interval must
            # still leave a timeline on disk for postmortems
            self.timeline.export(self._timeline_path)
        self._flush_telemetry()
        return state, metrics

    # -- multi-slice hierarchical DP ---------------------------------------
    def _slice_step(self, state, tok, tgt, step: int):
        """One hierarchical step: in-slice grads from the jitted
        grad_fn, cross-slice mean over DCN (tolerating an absent
        slice — degraded mode), optimizer update from the fleet mean.
        The pre-update ``state`` doubles as the rejoin-handoff payload
        the fleet leader may publish for a re-formed slice."""
        import jax

        grads, raw_metrics = self.trainer.grad_step(state, tok, tgt)
        leaves, treedef = jax.tree.flatten(grads)
        host_leaves = [np.asarray(leaf) for leaf in leaves]

        def _state_leaves():
            return [np.asarray(leaf) for leaf in jax.tree.leaves(state)]

        reduced, info = self._slice_sync.reduce(
            host_leaves, step, state_leaves_fn=_state_leaves)
        if info.get("degraded") or info.get("stalled_s"):
            obs.get_flight_recorder().record_event(
                "train_degraded_step", step=step,
                present=info.get("present"), absent=info.get("absent"),
                stalled_s=round(float(info.get("stalled_s", 0.0)), 1))
        fleet_grads = jax.tree.unflatten(treedef, [
            jax.device_put(leaf, sharding)
            for leaf, sharding in zip(
                reduced,
                jax.tree.leaves(self.trainer.state_shardings.params))
        ])
        state, apply_metrics = self.trainer.apply_grads(state,
                                                        fleet_grads)
        if self._steptrace is not None and info.get("trace"):
            import time as _time

            # the sync's clock() marks share the loop's monotonic
            # domain; apply-dispatch end completes the decomposition
            stashed = dict(info["trace"])
            stashed["apply_done"] = _time.monotonic()
            self._last_sync_trace = stashed
        raw_metrics = dict(raw_metrics)
        raw_metrics.update(apply_metrics)
        return state, raw_metrics

    def _trace_generation(self) -> int:
        """The membership episode steptrace records group under: the
        world epoch the slice sync saw last, else the applied plan's
        epoch, else 0 (static single-slice world)."""
        if self._slice_sync is not None:
            epoch = self._slice_sync.world_epoch
            if epoch >= 0:
                return epoch
        if self._shard_plan is not None:
            try:
                return int(self._shard_plan.get("epoch", 0) or 0)
            except (TypeError, ValueError):
                pass
        return 0

    def _record_steptrace(self, step: int, t_step: float, t_data: float,
                          t_compute_end: float, ckpt_s: float) -> None:
        """Build one per-step trace record from the loop's monotonic
        marks (+ the stashed SliceGradSync decomposition). Hot path:
        a handful of float ops and one bounded-ring append."""
        import time as _time

        now_mono = _time.monotonic()
        # local wall-clock anchor for the step start, derived from the
        # same monotonic domain as every mark (a wall-clock step between
        # t_step and now lands in the offset estimate, not the phases)
        t0_wall = _time.time() - (now_mono - t_step)
        data_d = max(0.0, t_data - t_step)
        h2d_d = max(0.0, float(getattr(self.trainer,
                                       "last_shard_batch_s", 0.0)))
        phases = [("data_wait", 0.0, data_d), ("h2d", data_d, h2d_d)]
        cursor = data_d + h2d_d
        peers = None
        stashed, self._last_sync_trace = self._last_sync_trace, None
        if stashed is not None:
            ready = stashed.get("grads_ready", t_compute_end) - t_step
            post = max(ready, stashed.get("local_post", 0.0) - t_step)
            coll = max(post, stashed.get("collect_done", 0.0) - t_step)
            apply_end = max(coll,
                            stashed.get("apply_done", t_compute_end)
                            - t_step)
            phases.append(("compute", cursor, max(0.0, ready - cursor)))
            phases.append(("local_post", ready, post - ready))
            phases.append(("cross_slice_wait", post, coll - post))
            phases.append(("apply", coll, apply_end - coll))
            cursor = max(cursor, apply_end)
            raw_peers = stashed.get("peers") or {}
            if raw_peers:
                peers = {sid: max(0.0, t - t_step)
                         for sid, t in raw_peers.items()}
        else:
            compute_end = max(cursor, t_compute_end - t_step)
            phases.append(("compute", cursor, compute_end - cursor))
            cursor = compute_end
        if ckpt_s > 0:
            phases.append(("checkpoint", cursor, ckpt_s))
        self._steptrace.record(step, self._trace_generation(), t0_wall,
                               phases, peers=peers)

    def _maybe_slice_catch_up(self, state, start_step: int, sampler
                              ) -> Tuple[Any, int]:
        """A re-formed slice restored at the checkpointed step while
        the fleet kept (degraded-mode) stepping: adopt the fleet-current
        state a surviving slice leader publishes over DCN, so this
        slice resumes in lockstep instead of re-treading steps the
        survivors already took."""
        import jax

        result = self._slice_sync.catch_up(start_step)
        if result is None:
            return state, start_step
        leaves, fleet_step = result
        template_leaves, treedef = jax.tree.flatten(state)
        if len(leaves) != len(template_leaves):
            logger.error(
                "fleet state handoff has %d leaves, local state %d: "
                "model mismatch — ignoring the handoff",
                len(leaves), len(template_leaves))
            return state, start_step
        shardings = jax.tree.leaves(self.trainer.state_shardings)
        adopted = jax.tree.unflatten(treedef, [
            jax.device_put(
                np.asarray(leaf).astype(tmpl.dtype).reshape(tmpl.shape),
                sharding)
            for leaf, tmpl, sharding in zip(leaves, template_leaves,
                                            shardings)
        ])
        if sampler is not None:
            for _ in range(max(0, fleet_step - start_step)):
                sampler.record_batch(self.global_batch)
        self.last_restore_timings["catch_up_steps"] = float(
            fleet_step - start_step)
        return adopted, fleet_step

    # -- preemption drain --------------------------------------------------
    def _consume_drain(self, drain: Dict[str, Any], step, state,
                       sampler) -> None:
        """Act on a drain/checkpoint request from the agent at a step
        boundary. ``exit=True`` (preemption): deadline-bounded emergency
        save, flush the postmortem, and leave with the clean-drain exit
        code (raises :class:`DrainExit`). ``exit=False`` (the master's
        urgent ``checkpoint`` fan-out): save now, keep training."""
        import time as _time

        deadline = float(drain.get("deadline", 0.0) or 0.0)
        reason = str(drain.get("reason", ""))
        exit_worker = bool(drain.get("exit", True))
        recorder = obs.get_flight_recorder()
        recorder.record_event(
            "train_drain", step=step, deadline=deadline,
            exit=exit_worker, reason=reason[:256])
        logger.warning(
            "drain request at step %d (deadline in %.0fs, exit=%s): %s",
            step,
            max(0.0, deadline - _time.time()) if deadline else -1.0,
            exit_worker, reason or "-")
        outcome = "no-checkpointer"
        data_state = self._data_state(sampler)
        if self.checkpointer is not None:
            # the deadline is a hard bound only on the way OUT (this
            # VM dies then). A survivor's save-and-continue inherits
            # the draining PEER's deadline — advisory at best: this
            # worker is not dying, and skipping/aborting its save
            # because the peer's window is short defeats the fan-out
            outcome = self.checkpointer.save_emergency(
                step, state, data_state,
                deadline=deadline if exit_worker else 0.0)
            if outcome == "saved" and not exit_worker:
                # a survivor's save-and-continue: mirror the cut into
                # the peer cache too — this survivor is exactly who the
                # departing rank's replacement will restore from. The
                # exiting path skips it: this host's memory dies with
                # the VM.
                self._stage_peer(step, state, data_state)
        elif exit_worker:
            logger.error("drain with no checkpointer configured: "
                         "exiting WITHOUT saving (progress since the "
                         "last external save is lost)")
        if not exit_worker:
            self._drain_source.acknowledge(int(drain.get("seq", 0) or 0))
            return
        # the way out: postmortem + telemetry first, then the distinct
        # clean-drain exit the agent classifies as non-failure
        if self._timeline_path:
            self.timeline.export(self._timeline_path)
        recorder.record_event("train_drained", step=step,
                              checkpoint=outcome)
        self._flush_telemetry()
        recorder.dump(reason="drain")
        logger.info("drained at step %d (checkpoint: %s); exiting %d",
                    step, outcome, WorkerExit.DRAIN)
        raise DrainExit(reason)

    # -- peer-state staging --------------------------------------------
    def _stage_peer(self, step: int, state, data_state) -> None:
        """Mirror the just-saved state into the host-RAM peer cache.
        The step loop pays only the device→host copy (the arrays may be
        donated away by the next step); file writes + CRCs run on the
        store's background writer. Best-effort: the loop survives a
        full cache disk."""
        if self._peer_store is None:
            return
        import time as _time

        t0 = _time.monotonic()
        with obs.span("peer_stage", {"step": step}) as stage_span:
            staged = self._peer_store.stage(step, state, data_state,
                                            defer_write=True)
            stage_span.set_attr("staged", staged)
        obs.get_registry().gauge(
            "dlrover_tpu_peer_stage_seconds",
            "Step-loop wall-clock of the last peer-state staging "
            "(host copy only; the write is deferred)").set(
            round(_time.monotonic() - t0, 3))

    # -- progress reporting ------------------------------------------------
    def _report_progress(self, step: int) -> None:
        """Report-interval bookkeeping: ship the step report (with the
        timeline's windowed speed evidence), export the timeline ring
        and the per-chip HBM stats for the agent. All best-effort — the
        step loop must survive a dead master and a full disk."""
        stats = self.timeline.window_stats(
            self.config.report_interval_steps)
        mean_step = stats.get("mean_step_s", 0.0)
        # achieved-vs-peak over the window: the step report's MFU field
        # feeds the master's per-rank gauge and the collapse rule
        self._maybe_cross_check_flops()
        tokens_per_step = self.global_batch * self.config.seq_len
        mfu = obs.mfu.achieved_mfu(
            tokens_per_step / mean_step if mean_step > 0 else -1.0,
            self._flops_per_token, self._peak_flops_total)
        degraded = (self._slice_sync.drain_unreported()
                    if self._slice_sync is not None else 0)
        if self.prefetch_tuner is not None:
            self.prefetch_tuner.observe(
                stats.get("data_wait_fraction", -1.0))
        # device-truth HBM window peak (0 = backend has no memory
        # stats): drained per report so the master sees each window's
        # watermark, not a stale lifetime number
        hbm = self.device_telemetry.drain()
        # calibration attributes this window's timing by the plan the
        # loop ACTUALLY applied: -2 (fallback / no plan / batch-only
        # replica mode, which runs a full local replica rather than
        # the stamped mesh) is dropped by the master rather than
        # contaminating the stamped shape
        plan_gen = (int(self._shard_plan.get("generation", 0) or 0)
                    if self._shard_plan is not None
                    and self._replan_applied == "mesh+batch" else -2)
        try:
            self.client.report_global_step(
                step, step_time_s=mean_step,
                data_wait_fraction=stats.get("data_wait_fraction", -1.0),
                mfu=mfu, degraded_steps=degraded,
                hbm_peak_bytes=hbm.get("hbm_peak_bytes", 0.0),
                plan_generation=plan_gen)
        except Exception:  # noqa: BLE001 — droppable by contract
            # the degraded tally must not vanish with a dropped report
            if degraded and self._slice_sync is not None:
                self._slice_sync.degraded_unreported += degraded
        # tail-only AND wall-clock throttled on the hot path: the
        # write+rename alone costs ~1 ms on slow filesystems, so fast
        # steps with a short report interval would blow the < 1 %
        # overhead budget; at most one export/second bounds the cost at
        # ~0.1 % of training regardless of step time. The end-of-run
        # flush writes the whole ring.
        import time as _time

        now = _time.monotonic()
        if self._timeline_path and now - self._timeline_exported_at >= 1.0:
            self._timeline_exported_at = now
            self.timeline.export(
                self._timeline_path,
                last_n=2 * self.config.report_interval_steps)
        if self._steptrace is not None and self.client is not None:
            # periodic clock refresh rides the report cadence (one RPC,
            # rate-limited by the probe interval — never per step)
            from dlrover_tpu.common.config import Context as _Ctx

            self._clock_sync.maybe_probe(
                _Ctx.singleton().steptrace_probe_interval_s)
        try:
            from dlrover_tpu.agent.monitor import export_chip_stats

            # duty-cycle proxy wants the per-step seconds the DEVICE is
            # plausibly busy: the whole step minus the phases where the
            # host is starving it (input wait, blocking checkpoint).
            # Passing total step time would make duty ≈ 100% even on a
            # worker spending most of each step waiting on data.
            busy_fraction = max(
                0.0, 1.0 - max(0.0, stats.get("data_wait_fraction", 0.0))
                - stats.get("checkpoint_fraction", 0.0))
            export_chip_stats(step=step,
                              step_time_s=mean_step * busy_fraction)
        except Exception:  # noqa: BLE001 — stats are advisory
            pass

    def _data_state(self, sampler) -> Dict[str, Any]:
        data_state: Dict[str, Any] = {}
        if sampler is not None:
            data_state["sampler"] = sampler.state_dict()
        if self.client is not None:
            try:
                shards = self.client.get_shard_checkpoint("")
                # the master answers "" when no dataset is registered
                # (purely local data): nothing to restore later
                if shards:
                    data_state["shards"] = shards
            except Exception:
                pass
        return data_state

    def _flush_telemetry(self) -> None:
        if self.client is not None:
            self._span_exporter.flush_to(self.client)
            if self._steptrace is not None:
                self._steptrace.flush_to(self.client)

    def close(self) -> None:
        self._flush_telemetry()
        obs.remove_span_sink(self._span_exporter)
        if self._peer_store is not None:
            # a deferred stage write still in flight must land before
            # the process goes away (the whole point of the mirror)
            self._peer_store.flush()
        if self.checkpointer is not None:
            self.checkpointer.close()
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
