"""Sharded training step builder with gradient accumulation.

Capability parity: ElasticTrainer's fixed-global-batch gradient accumulation
(dlrover/trainer/torch/elastic/trainer.py:53-139 GradientState/no_sync
machinery) — TPU re-design: microbatches are a `lax.scan` inside ONE jitted
program; the whole state (params + optimizer) is laid out by logical-axis
rules over the mesh, so DP/FSDP/TP are a table change, not a wrapper class.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.parallel.mesh import use_mesh
from dlrover_tpu.parallel.moe import moe_aux_loss
from dlrover_tpu.parallel.sharding import (
    DEFAULT_RULES,
    mesh_shardings,
    sanitize_shardings,
)


def abstract_state_with_shardings(abstract: Any, shardings: Any) -> Any:
    """Attach shardings to an eval_shape'd state tree — the checkpoint
    restore target shared by the dense and pipelined trainers."""
    return jax.tree.map(
        lambda leaf, sharding: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=sharding),
        abstract, shardings)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


@dataclasses.dataclass
class ShardedTrainer:
    """A lowered (mesh-specific) training program.

    Rebuild via `build_trainer` after an elastic world resize — compiled
    programs are mesh-shape-specific (SURVEY.md §7 'hard parts').
    """

    mesh: Mesh
    init_fn: Callable[[jax.Array], TrainState]
    step_fn: Callable[..., Tuple[TrainState, dict]]
    state_shardings: Any
    batch_sharding: NamedSharding
    accum_steps: int
    micro_batch: int
    batch_abstract: Optional[jax.ShapeDtypeStruct] = None
    # split-step programs (build_trainer(split_grad_apply=True)): the
    # host-level cross-slice gradient sync (parallel/dcn_sync.py) needs
    # the in-slice-reduced gradient OUT of the program and the fleet-
    # reduced gradient back IN before the optimizer update. None on
    # fused-step trainers.
    grad_fn: Any = dataclasses.field(default=None, repr=False)
    apply_fn: Any = dataclasses.field(default=None, repr=False)
    _compiled_step: Any = dataclasses.field(default=None, repr=False)
    precompile_timings: dict = dataclasses.field(default_factory=dict)
    last_used_aot: bool = False
    # host wall-clock of the last step()/shard_batch() calls — the
    # "compute (dispatch)" / "h2d" phases of the step timeline
    # (obs/timeline.py), measured at the source so the loop's own
    # bookkeeping never pollutes the attribution
    last_step_dispatch_s: float = 0.0
    last_shard_batch_s: float = 0.0

    def init(self, rng: jax.Array) -> TrainState:
        return self.init_fn(rng)

    def abstract_state(self, rng: jax.Array) -> TrainState:
        """Abstract TrainState (shapes + shardings, nothing allocated) —
        the checkpoint-restore target (reshard-on-restore)."""
        return abstract_state_with_shardings(
            jax.eval_shape(self.init_fn, rng), self.state_shardings)

    def precompile(self, rng: Optional[jax.Array] = None) -> None:
        """AOT-compile the train step from abstract inputs (trace +
        lower + XLA compile or persistent-cache load), so a respawned
        worker can overlap compilation with its checkpoint read instead
        of serializing re-jit after it (the measured ~155 s tail of the
        262 s at-scale restore, docs/benchmarks.md). Safe to call from a
        background thread; `step` uses the compiled executable when
        present and falls back to the jitted path on any mismatch."""
        if self._compiled_step is not None or self.batch_abstract is None:
            return
        import time as _time

        from dlrover_tpu import obs

        abstract = self.abstract_state(
            jax.random.PRNGKey(0) if rng is None else rng)
        with obs.span("recompile", {"phase": "aot"}) as aot_span:
            t0 = _time.monotonic()
            lowered = self.step_fn.lower(
                abstract, self.batch_abstract, self.batch_abstract)
            t1 = _time.monotonic()
            compiled = lowered.compile()
            t2 = _time.monotonic()
            self.precompile_timings = {
                "trace_lower_s": round(t1 - t0, 2),
                "compile_or_cache_load_s": round(t2 - t1, 2),
            }
            aot_span.set_attr("trace_lower_s",
                              self.precompile_timings["trace_lower_s"])
            aot_span.set_attr(
                "compile_or_cache_load_s",
                self.precompile_timings["compile_or_cache_load_s"])
        self._compiled_step = compiled

    def step(self, state: TrainState, tokens, targets):
        import time as _time

        t0 = _time.monotonic()
        try:
            return self._step_inner(state, tokens, targets)
        finally:
            self.last_step_dispatch_s = _time.monotonic() - t0

    def _step_inner(self, state: TrainState, tokens, targets):
        if self._compiled_step is not None:
            try:
                out = self._compiled_step(state, tokens, targets)
                self.last_used_aot = True
                return out
            except (TypeError, ValueError) as e:
                # pre-dispatch signature/layout mismatch vs the AOT
                # arguments (raised before buffers are donated): the
                # jitted path recompiles correctly. Runtime errors (OOM,
                # XlaRuntimeError) propagate — state may already be
                # donated, so re-running would only mask the real error.
                from dlrover_tpu.common.log import default_logger

                default_logger.warning(
                    "AOT-compiled step rejected its arguments (%s); "
                    "falling back to the jitted path", e)
                self._compiled_step = None
        self.last_used_aot = False
        return self.step_fn(state, tokens, targets)

    def grad_step(self, state: TrainState, tokens, targets):
        """Forward+backward only: (slice-mean grads, metrics). The
        caller reduces the grads across slices (host-level DCN sync)
        before `apply_grads`. Only on split-built trainers."""
        import time as _time

        if self.grad_fn is None:
            raise RuntimeError("trainer was not built with "
                               "split_grad_apply=True")
        t0 = _time.monotonic()
        try:
            return self.grad_fn(state, tokens, targets)
        finally:
            self.last_step_dispatch_s = _time.monotonic() - t0

    def apply_grads(self, state: TrainState, grads):
        """Optimizer update from (fleet-reduced) grads → (new_state,
        metrics)."""
        if self.apply_fn is None:
            raise RuntimeError("trainer was not built with "
                               "split_grad_apply=True")
        return self.apply_fn(state, grads)

    def shard_batch(self, tokens, targets):
        """Host numpy (global_batch, seq) → device arrays shaped
        (accum, micro, seq) with the micro axis over (data, fsdp)."""
        import time as _time

        t0 = _time.monotonic()
        accum, micro = self.accum_steps, self.micro_batch
        tokens = tokens.reshape(accum, micro, *tokens.shape[1:])
        targets = targets.reshape(accum, micro, *targets.shape[1:])
        put = lambda x: jax.device_put(x, self.batch_sharding)
        result = put(tokens), put(targets)
        self.last_shard_batch_s = _time.monotonic() - t0
        return result


def build_trainer(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    sample_batch: jax.Array,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    accum_steps: int = 1,
    micro_batch: int = 1,
    rules: Optional[Sequence] = None,
    donate_state: bool = True,
    offload_opt_state: bool = False,
    rng_seed: int = 0,
    grad_reduce_bits: int = 0,
    grad_reduce_axis: Optional[str] = None,
    split_grad_apply: bool = False,
) -> ShardedTrainer:
    """Lower (model, optimizer, mesh) into init/step programs.

    sample_batch: one microbatch of tokens, shape (micro_batch, seq) — used
    only for shape inference.

    offload_opt_state: keep the optimizer state in HOST memory
    (pinned_host memory kind) — the TPU-native equivalent of the
    reference's CPU-offloaded Adam (atorch/optim/adam_offload.py): the
    moments' shardings carry the host memory kind and XLA inserts the
    host↔HBM transfers around the update, freeing ~2/3 of the train
    state's HBM at the cost of PCIe/DMA traffic per step.

    grad_reduce_bits: 8/4 = the gradient mean over ``grad_reduce_axis``
    runs through the quantized collective
    (parallel/quant_collectives.py, the reference quant_reduce.cu
    analog) instead of XLA's implicit fp psum: the whole step is wrapped
    in a shard_map manual over that one axis, every other axis stays
    auto. 0 = exact reduce (default).

    grad_reduce_axis: None resolves hierarchically — the ``dcn`` axis
    when the mesh spans slices (dcn > 1), else ``data``. A dcn reduce
    makes the gradient sync explicitly two-level: the in-slice mean
    rides XLA's implicit psum over the (data, fsdp) axes inside each
    slice block, then the cross-slice mean (all-)reduces over the
    manual dcn axis — quantized when ``grad_reduce_bits`` asks for it,
    exact pmean otherwise.

    split_grad_apply: additionally build ``grad_fn``/``apply_fn`` —
    the two halves of the step around a HOST-level cross-slice
    gradient sync (parallel/dcn_sync.py): grad_fn returns the
    in-slice-reduced grads, the host exchanges them over DCN
    (tolerating an absent slice), apply_fn applies the fleet mean.
    """
    rules = list(rules if rules is not None else DEFAULT_RULES)

    def _init_boxed(rng):
        variables = model.init(rng, sample_batch)
        params = variables["params"]
        # optax maps over the boxed tree, so optimizer moments inherit the
        # logical axis annotations (→ FSDP shards them like the params)
        opt_state = tx.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    # The mesh context is entered INSIDE every traced function so model
    # code can reach the concrete mesh at trace time (current_mesh() —
    # ring/Ulysses attention build an inner shard_map from it), including
    # re-traces from eval_shape in the checkpoint-restore path.
    with use_mesh(mesh):
        abstract_boxed = jax.eval_shape(
            _init_boxed, jax.random.key(0)
        )
    state_shardings = mesh_shardings(abstract_boxed, mesh, rules)
    # factored optimizers (adafactor) produce state leaves whose rank
    # differs from the param that named their axes — replicate those
    state_shardings = sanitize_shardings(
        state_shardings, nn.unbox(abstract_boxed), mesh)
    if offload_opt_state:
        from dlrover_tpu.common.jax_compat import host_memory_kind

        host_kind = host_memory_kind(mesh.devices.flat[0])
        abstract_opt = nn.unbox(abstract_boxed).opt_state
        state_shardings = state_shardings.replace(
            opt_state=jax.tree.map(
                # scalars (step counters) stay on device: XLA's SPMD
                # partitioner rejects memory-kind annotations on them
                lambda s, a: s if a.ndim == 0 else NamedSharding(
                    mesh, s.spec, memory_kind=host_kind),
                state_shardings.opt_state, abstract_opt,
            ))
    # Batch (accum, micro, seq): micro over the joint dp axes (dcn +
    # data + fsdp — cross-slice replicas outermost), seq over the
    # sequence axis (a no-op at sequence=1; shards inputs for SP runs).
    from dlrover_tpu.parallel.mesh import data_axes

    batch_shard = NamedSharding(
        mesh, P(None, data_axes(mesh), MeshAxis.SEQUENCE)
    )

    def _init(rng):
        with use_mesh(mesh):
            return nn.unbox(_init_boxed(rng))

    init_fn = jax.jit(_init, out_shardings=state_shardings)

    def _train_step(state: TrainState, tokens, targets):
        # activation logical-constraints in the models resolve through
        # these rules (no-ops without this context); with-block so a
        # trace-time exception never leaks flax's global rules stack
        with use_mesh(mesh), nn.logical_axis_rules(rules):
            return _train_step_body(state, tokens, targets)

    def _accumulate(state: TrainState, tokens, targets):
        """The microbatch scan: (loss_sum, f32 grad_sum) before any
        explicit cross-axis reduce or the optimizer update — the shared
        core of the fused step and the split grad_fn."""
        params = state.params
        # Deterministic per-step rng streams for stochastic model paths
        # (MoE gating jitter, dropout): folded from the step counter so
        # every restart replays identically, and identical across
        # replicas as SPMD single-program semantics require.
        step_key = jax.random.fold_in(jax.random.PRNGKey(rng_seed),
                                      state.step)

        def micro_step(carry, micro):
            loss_acc, grad_acc = carry
            tok, tgt, idx = micro
            micro_key = jax.random.fold_in(step_key, idx)
            rngs = {"gating": jax.random.fold_in(micro_key, 0),
                    "dropout": jax.random.fold_in(micro_key, 1)}

            def compute_loss(p):
                # mutable "losses": models sow auxiliary losses there
                # (MoE router balancing, parallel/moe.py:172); for models
                # that never sow, the collection is empty and the sum is
                # 0 — one generic path covers both
                logits, mutables = model.apply(
                    {"params": p}, tok, mutable=["losses"], rngs=rngs)
                return loss_fn(logits, tgt) + moe_aux_loss(mutables)

            loss, grads = jax.value_and_grad(compute_loss)(params)
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
            )
            return (loss_acc + loss, grad_acc), None

        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, grad_sum), _ = jax.lax.scan(
            micro_step, (jnp.zeros((), jnp.float32), zero_grads),
            (tokens, targets, jnp.arange(accum_steps)),
        )
        return loss_sum, grad_sum

    def _apply_body(state: TrainState, grads):
        """Optimizer update from already-reduced grads (param dtype):
        (new_state, grad_norm)."""
        updates, new_opt = tx.update(grads, state.opt_state,
                                     state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt)
        return new_state, optax.global_norm(grads)

    def _train_step_body(state: TrainState, tokens, targets,
                         grad_reduce=None):
        loss_sum, grad_sum = _accumulate(state, tokens, targets)
        if grad_reduce is not None:
            # explicit (possibly quantized) mean over the manual reduce
            # axis — the cross-slice half of the hierarchical sync; the
            # in-slice half already happened through XLA's implicit
            # psum over the auto (data, fsdp) axes. The loss metric
            # reduces exactly (it's a scalar).
            grad_sum = grad_reduce(grad_sum)
            loss_sum = jax.lax.pmean(loss_sum, grad_reduce_axis)
        grads = jax.tree.map(
            lambda g, p: (g / accum_steps).astype(p.dtype), grad_sum,
            state.params
        )
        new_state, grad_norm = _apply_body(state, grads)
        metrics = {
            "loss": loss_sum / accum_steps,
            "grad_norm": grad_norm,
        }
        return new_state, metrics

    if grad_reduce_axis is None:
        # hierarchical by default: a mesh spanning slices reduces over
        # the dcn axis (in-slice implicit + cross-slice explicit)
        grad_reduce_axis = (MeshAxis.DCN
                            if mesh.shape.get(MeshAxis.DCN, 1) > 1
                            else MeshAxis.DATA)
    n_reduce = mesh.shape.get(grad_reduce_axis, 1)
    from dlrover_tpu.common.jax_compat import HAS_PARTIAL_AUTO, shard_map

    # the dcn axis always reduces explicitly (the hierarchical
    # contract), quantized or not; other axes only when quantized
    wrap_reduce = n_reduce > 1 and (
        bool(grad_reduce_bits) or grad_reduce_axis == MeshAxis.DCN)
    if (wrap_reduce and not HAS_PARTIAL_AUTO
            and len([a for a, n in mesh.shape.items() if n > 1]) > 1):
        # the explicit reduce needs a shard_map manual over ONE axis of
        # a multi-axis mesh; without partial-auto support that program
        # cannot be built — train exactly instead of not at all (the
        # flat implicit mean over (dcn, data, fsdp) is numerically the
        # hierarchical mean of equal-size slice means)
        from dlrover_tpu.common.log import default_logger

        default_logger.warning(
            "grad reduce over %r (bits=%d) needs a partial-auto "
            "shard_map this jax lacks; falling back to the exact flat "
            "reduce", grad_reduce_axis, grad_reduce_bits)
        grad_reduce_bits = 0
        wrap_reduce = False
    if wrap_reduce:
        from jax.sharding import PartitionSpec

        from dlrover_tpu.parallel.quant_collectives import quantized_pmean

        # Manual ONLY over the reduce axis: every other axis (fsdp/tp/…)
        # stays auto so XLA keeps intra-slice sharding + collectives.
        # Activation rules must not name the manual axis — strip it.
        def _strip(axes):
            if axes is None:
                return None
            if isinstance(axes, str):
                return None if axes == grad_reduce_axis else axes
            kept = tuple(a for a in axes if a != grad_reduce_axis)
            return kept or None

        rules_local = [(name, _strip(axes)) for name, axes in rules]

        def _reduce(tree):
            return quantized_pmean(tree, grad_reduce_axis, n_reduce,
                                   bits=grad_reduce_bits)

        def _body_local(state, tokens, targets):
            with use_mesh(mesh), nn.logical_axis_rules(rules_local):
                return _train_step_body(state, tokens, targets,
                                        grad_reduce=_reduce)

        state_manual_spec = jax.tree.map(lambda _: PartitionSpec(),
                                         state_shardings)
        batch_manual_spec = PartitionSpec(None, grad_reduce_axis)
        wrapped = shard_map(
            _body_local,
            mesh=mesh,
            in_specs=(state_manual_spec, batch_manual_spec,
                      batch_manual_spec),
            out_specs=(state_manual_spec, PartitionSpec()),
            axis_names=frozenset({grad_reduce_axis}),
            # the updated state IS invariant over the reduce axis (it is
            # computed from the reduced grads), but all_gather-derived
            # values type as varying — the static check can't see this
            check_vma=False,
        )
        step_impl = wrapped
    else:
        step_impl = _train_step

    step_fn = jax.jit(
        step_impl,
        in_shardings=(state_shardings, batch_shard, batch_shard),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate_state else (),
    )

    grad_fn = apply_fn = None
    if split_grad_apply:
        # the two halves around a host-level cross-slice sync: grad_fn's
        # output is the in-slice mean (XLA's implicit psum over the auto
        # dp axes of THIS program's world — one slice in the elastic
        # multi-world mode), apply_fn takes the fleet-reduced mean back.
        # grad_fn must NOT donate the state: apply_fn still reads it.
        def _grad_only(state, tokens, targets):
            with use_mesh(mesh), nn.logical_axis_rules(rules):
                loss_sum, grad_sum = _accumulate(state, tokens, targets)
                grads = jax.tree.map(
                    lambda g, p: (g / accum_steps).astype(p.dtype),
                    grad_sum, state.params)
                return grads, {"loss": loss_sum / accum_steps}

        def _apply_only(state, grads):
            with use_mesh(mesh), nn.logical_axis_rules(rules):
                new_state, grad_norm = _apply_body(state, grads)
                return new_state, {"grad_norm": grad_norm}

        grads_shardings = state_shardings.params
        # NO donation on grad_fn by design: the same state is re-read
        # by apply_fn after the host-level cross-slice exchange
        grad_fn = jax.jit(  # graftlint: disable=GL104
            _grad_only,
            in_shardings=(state_shardings, batch_shard, batch_shard),
            out_shardings=(grads_shardings, None),
        )
        apply_fn = jax.jit(
            _apply_only,
            in_shardings=(state_shardings, grads_shardings),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate_state else (),
        )

    return ShardedTrainer(
        mesh=mesh,
        init_fn=init_fn,
        step_fn=step_fn,
        state_shardings=state_shardings,
        batch_sharding=batch_shard,
        accum_steps=accum_steps,
        micro_batch=micro_batch,
        grad_fn=grad_fn,
        apply_fn=apply_fn,
        batch_abstract=jax.ShapeDtypeStruct(
            (accum_steps, micro_batch, *sample_batch.shape[1:]),
            jnp.int32, sharding=batch_shard),
    )


def choose_accumulation(global_batch: int, dp_size: int,
                        max_micro_per_replica: int) -> Tuple[int, int]:
    """Pick (accum_steps, micro_batch_global) holding the global batch fixed
    as the world resizes (reference: ElasticTrainer trainer.py:225 —
    acc = max_workers / cur_workers).

    micro_batch_global = global_batch / accum must divide by dp_size and fit
    per-replica memory (micro/dp ≤ max_micro_per_replica).
    """
    if global_batch % dp_size:
        raise ValueError(
            f"global batch {global_batch} not divisible by dp size {dp_size}"
        )
    per_replica_total = global_batch // dp_size
    accum = 1
    while (per_replica_total % accum
           or per_replica_total // accum > max_micro_per_replica):
        accum += 1
        if accum > per_replica_total:
            accum = per_replica_total
            break
    return accum, global_batch // accum
