"""Elastic embedding training — the parameter-server path, TPU-reframed.

Capability parity: the reference's TF/PS elastic training (EstimatorExecutor
trainer/tensorflow/executor/estimator_executor.py:52, PS failover
tensorflow_failover.py:33, ElasticPsService cluster-version arbitration
master/elastic_training/elastic_ps.py:18). SURVEY.md §7 calls for the
idiomatic TPU reframing: there are no parameter-server processes — the
embedding table is a sharded array over the fsdp axis, updated row-sparsely
(dlrover_tpu/optim/sparse.py), and "PS failover" becomes cluster-version
arbitration + checkpoint-restore of the table, reusing the master's
ElasticPsService + SyncService machinery unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from dlrover_tpu.common.constants import MeshAxis


@dataclasses.dataclass(frozen=True)
class EmbeddingConfig:
    vocab_size: int
    embed_dim: int
    combiner: str = "none"      # "none" | "mean" | "sum" (multi-hot bags)
    param_dtype: Any = jnp.float32


class ShardedEmbedding(nn.Module):
    """Embedding table sharded over the fsdp axis by rows (the PS shard
    dimension). Lookup gathers ride XLA's all-to-all across shards."""

    cfg: EmbeddingConfig

    @nn.compact
    def __call__(self, ids: jax.Array) -> jax.Array:
        table = self.param(
            "table",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.01), ("embed_rows", "embed_cols")),
            (self.cfg.vocab_size, self.cfg.embed_dim),
            self.cfg.param_dtype,
        )
        out = jnp.take(table, ids, axis=0)
        if self.cfg.combiner in ("mean", "sum") and ids.ndim >= 2:
            # bag lookup: (..., multi_hot, dim) → (..., dim)
            reduce = jnp.mean if self.cfg.combiner == "mean" else jnp.sum
            out = reduce(out, axis=-2)
        return out


# logical-axis rules for the PS path: rows over fsdp (the "server shard"
# dim), columns replicated
EMBEDDING_RULES = [
    ("embed_rows", MeshAxis.FSDP),
    ("embed_cols", None),
]


@dataclasses.dataclass
class ReconcileResult:
    """Outcome of ElasticEmbeddingTrainer.maybe_reconcile: the (possibly
    restored) state, whether a restore happened, and the checkpoint's step
    and data position — the caller must roll its step counter and sampler
    back with the parameters."""

    state: Any
    reconciled: bool
    step: int = 0
    data_state: Any = None


class EmbeddingFailoverClient:
    """Worker-side cluster-version arbitration.

    Capability parity: FailoverClient
    (trainer/tensorflow/failover/failover_client.py:21) +
    TensorflowFailover (:91-144): the worker adopts the global version at
    start, publishes it as its local version, and watches for the global
    version to advance past it — the master's PsFailoverCallback bumps it
    when a state holder dies. A lagging local version means this worker's
    view of the sharded state is stale and it must reconcile (restore from
    the latest committed checkpoint) before training on.
    """

    def __init__(self, master_client, task_type: str = "worker"):
        self._client = master_client
        self._task_type = task_type
        self.local_version = 0

    def start(self) -> int:
        """Adopt the current global version and publish it as local."""
        self.local_version = self._client.get_cluster_version(
            "global", self._task_type)
        self._client.update_cluster_version(
            "local", self.local_version, self._task_type)
        return self.local_version

    def needs_reconcile(self) -> bool:
        return (self._client.get_cluster_version("global", self._task_type)
                > self.local_version)

    def complete_reconcile(self) -> int:
        """Adopt the (possibly again-advanced) global version after a
        successful restore and publish it."""
        self.local_version = self._client.get_cluster_version(
            "global", self._task_type)
        self._client.update_cluster_version(
            "local", self.local_version, self._task_type)
        return self.local_version

    def wait_reconciled_cluster(self, task_ids, timeout_s: float = 60.0
                                ) -> bool:
        """Block until every LIVE worker's published local version has
        caught up with the global version (the reference's sync-barrier
        around PS migration). ``task_ids`` is the live membership — take
        it from the current rendezvous world, NOT a count: relaunched
        nodes get fresh ids, so positional ranges would poll the dead."""
        import time as _time

        deadline = _time.time() + timeout_s
        while _time.time() < deadline:
            global_v = self._client.get_cluster_version(
                "global", self._task_type)
            locals_ok = all(
                self._client.get_cluster_version(
                    "local", self._task_type, task_id=i) >= global_v
                for i in task_ids)
            if locals_ok:
                return True
            _time.sleep(0.1)
        return False


class ElasticEmbeddingTrainer:
    """PS-style training loop core: sparse embedding + dense tower.

    Version arbitration contract (reference elastic_ps.py): workers call
    `client.update_cluster_version("local", v)` after restoring and train
    only once `get_cluster_version("global") >= local` — the master's
    ElasticPsService (master/sync_service.py) decides the global version.
    """

    def __init__(
        self,
        mesh: Mesh,
        embedding: ShardedEmbedding,
        dense_apply,                   # (dense_params, emb) -> loss inputs
        loss_fn,
        embed_tx: Optional[optax.GradientTransformation] = None,
        dense_tx: Optional[optax.GradientTransformation] = None,
    ):
        from dlrover_tpu.optim.sparse import row_sparse_adagrad

        self.mesh = mesh
        self.embedding = embedding
        self.dense_apply = dense_apply
        self.loss_fn = loss_fn
        # the PS-analog split: sparse optimizer for the table, dense
        # optimizer for everything else (exactly the reference's
        # sparse-PS / dense-worker split)
        self.embed_tx = embed_tx or row_sparse_adagrad(0.05)
        self.dense_tx = dense_tx or optax.adam(1e-3)

    def init(self, rng: jax.Array, sample_ids: jax.Array,
             dense_params: Any) -> Tuple[Any, Any, Any]:
        from dlrover_tpu.parallel.sharding import mesh_shardings

        abstract = jax.eval_shape(
            lambda: self.embedding.init(rng, sample_ids))
        shardings = mesh_shardings(abstract, self.mesh, EMBEDDING_RULES)
        variables = jax.jit(
            lambda: nn.unbox(self.embedding.init(rng, sample_ids)),
            out_shardings=shardings)()
        embed_params = variables["params"]
        return (embed_params, self.embed_tx.init(embed_params),
                self.dense_tx.init(dense_params))

    def build_step(self):
        embedding = self.embedding
        dense_apply = self.dense_apply
        loss_fn = self.loss_fn
        embed_tx, dense_tx = self.embed_tx, self.dense_tx

        # donate the threaded state: the table + moments dominate HBM in
        # the PS-analog path, and callers always rebind the returned tuple
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def step(embed_params, embed_opt, dense_params, dense_opt, ids,
                 labels):
            def compute(embed_p, dense_p):
                emb = embedding.apply({"params": embed_p}, ids)
                preds = dense_apply(dense_p, emb)
                return loss_fn(preds, labels)

            loss, (g_embed, g_dense) = jax.value_and_grad(
                compute, argnums=(0, 1))(embed_params, dense_params)
            eu, embed_opt = embed_tx.update(g_embed, embed_opt,
                                            embed_params)
            embed_params = optax.apply_updates(embed_params, eu)
            du, dense_opt = dense_tx.update(g_dense, dense_opt,
                                            dense_params)
            dense_params = optax.apply_updates(dense_params, du)
            return embed_params, embed_opt, dense_params, dense_opt, loss

        return step

    def maybe_reconcile(self, failover: EmbeddingFailoverClient,
                        checkpointer, state) -> "ReconcileResult":
        """The failover workflow the reference drives from
        tensorflow_failover.py:91-144, TPU-reframed: when the global
        cluster version advanced past this worker's local version (a
        state holder died), restore (embed_params, embed_opt,
        dense_params, dense_opt) from the latest committed checkpoint
        into the live shardings, adopt the version, and publish it.

        Call between steps; training must not proceed on a stale view
        once `needs_reconcile()` is true. The result carries the
        checkpoint's step and data_state so the caller rolls its step
        counter and sampler position back with the parameters. If no
        committed checkpoint exists, NOTHING is published (the worker
        stays marked stale) and `reconciled` is False — the caller
        should keep retrying or escalate.
        """
        if not failover.needs_reconcile():
            return ReconcileResult(state=state, reconciled=False)
        abstract = jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=leaf.sharding),
            state,
        )
        restored = checkpointer.restore(abstract)
        if restored is None:
            from dlrover_tpu.common.log import default_logger as logger

            logger.warning(
                "reconcile needed (global version ahead) but no committed "
                "checkpoint exists; staying stale")
            return ReconcileResult(state=state, reconciled=False)
        state, data_state, step = restored
        failover.complete_reconcile()
        return ReconcileResult(state=state, reconciled=True, step=step,
                               data_state=data_state)
