"""Elastic embedding training — the parameter-server path, TPU-reframed.

Capability parity: the reference's TF/PS elastic training (EstimatorExecutor
trainer/tensorflow/executor/estimator_executor.py:52, PS failover
tensorflow_failover.py:33, ElasticPsService cluster-version arbitration
master/elastic_training/elastic_ps.py:18). SURVEY.md §7 calls for the
idiomatic TPU reframing: there are no parameter-server processes — the
embedding table is a sharded array over the fsdp axis, updated row-sparsely
(dlrover_tpu/optim/sparse.py), and "PS failover" becomes cluster-version
arbitration + checkpoint-restore of the table, reusing the master's
ElasticPsService + SyncService machinery unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis


@dataclasses.dataclass(frozen=True)
class EmbeddingConfig:
    vocab_size: int
    embed_dim: int
    combiner: str = "none"      # "none" | "mean" | "sum" (multi-hot bags)
    param_dtype: Any = jnp.float32


class ShardedEmbedding(nn.Module):
    """Embedding table sharded over the fsdp axis by rows (the PS shard
    dimension). Lookup gathers ride XLA's all-to-all across shards."""

    cfg: EmbeddingConfig

    @nn.compact
    def __call__(self, ids: jax.Array) -> jax.Array:
        table = self.param(
            "table",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.01), ("embed_rows", "embed_cols")),
            (self.cfg.vocab_size, self.cfg.embed_dim),
            self.cfg.param_dtype,
        )
        out = jnp.take(table, ids, axis=0)
        if self.cfg.combiner in ("mean", "sum") and ids.ndim >= 2:
            # bag lookup: (..., multi_hot, dim) → (..., dim)
            reduce = jnp.mean if self.cfg.combiner == "mean" else jnp.sum
            out = reduce(out, axis=-2)
        return out


# logical-axis rules for the PS path: rows over fsdp (the "server shard"
# dim), columns replicated
EMBEDDING_RULES = [
    ("embed_rows", MeshAxis.FSDP),
    ("embed_cols", None),
]


class ElasticEmbeddingTrainer:
    """PS-style training loop core: sparse embedding + dense tower.

    Version arbitration contract (reference elastic_ps.py): workers call
    `client.update_cluster_version("local", v)` after restoring and train
    only once `get_cluster_version("global") >= local` — the master's
    ElasticPsService (master/sync_service.py) decides the global version.
    """

    def __init__(
        self,
        mesh: Mesh,
        embedding: ShardedEmbedding,
        dense_apply,                   # (dense_params, emb) -> loss inputs
        loss_fn,
        embed_tx: Optional[optax.GradientTransformation] = None,
        dense_tx: Optional[optax.GradientTransformation] = None,
    ):
        from dlrover_tpu.optim.sparse import row_sparse_adagrad

        self.mesh = mesh
        self.embedding = embedding
        self.dense_apply = dense_apply
        self.loss_fn = loss_fn
        # the PS-analog split: sparse optimizer for the table, dense
        # optimizer for everything else (exactly the reference's
        # sparse-PS / dense-worker split)
        self.embed_tx = embed_tx or row_sparse_adagrad(0.05)
        self.dense_tx = dense_tx or optax.adam(1e-3)

    def init(self, rng: jax.Array, sample_ids: jax.Array,
             dense_params: Any) -> Tuple[Any, Any, Any]:
        from dlrover_tpu.parallel.sharding import mesh_shardings

        abstract = jax.eval_shape(
            lambda: self.embedding.init(rng, sample_ids))
        shardings = mesh_shardings(abstract, self.mesh, EMBEDDING_RULES)
        variables = jax.jit(
            lambda: nn.unbox(self.embedding.init(rng, sample_ids)),
            out_shardings=shardings)()
        embed_params = variables["params"]
        return (embed_params, self.embed_tx.init(embed_params),
                self.dense_tx.init(dense_params))

    def build_step(self):
        embedding = self.embedding
        dense_apply = self.dense_apply
        loss_fn = self.loss_fn
        embed_tx, dense_tx = self.embed_tx, self.dense_tx

        @jax.jit
        def step(embed_params, embed_opt, dense_params, dense_opt, ids,
                 labels):
            def compute(embed_p, dense_p):
                emb = embedding.apply({"params": embed_p}, ids)
                preds = dense_apply(dense_p, emb)
                return loss_fn(preds, labels)

            loss, (g_embed, g_dense) = jax.value_and_grad(
                compute, argnums=(0, 1))(embed_params, dense_params)
            eu, embed_opt = embed_tx.update(g_embed, embed_opt,
                                            embed_params)
            embed_params = optax.apply_updates(embed_params, eu)
            du, dense_opt = dense_tx.update(g_dense, dense_opt,
                                            dense_params)
            dense_params = optax.apply_updates(dense_params, du)
            return embed_params, embed_opt, dense_params, dense_opt, loss

        return step
