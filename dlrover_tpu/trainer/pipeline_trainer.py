"""Pipelined transformer trainer: GPipe over stacked decoder layers.

Capability parity: atorch's pipeline-parallel training path (PiPPy
compile → stages → driver, distributed_pippy_compiler.py:378) and the
DeepSpeed 3D composition (ds_3d_parallel_optimization.py:53 — pipe ×
tensor × data in one topology). TPU re-design (scan-over-layers lineage):
decoder-layer params are stacked (num_stages, layers_per_stage, ...) with
the stage dim sharded over the `pipe` mesh axis AND their trailing dims
sharded over fsdp/tensor through the model's logical axis names — the
pipe shard_map is manual only over `pipe` (jax.shard_map axis_names), so
XLA keeps the stage-internal shardings and inserts the intra-stage
collectives. The forward runs the embedding, streams microbatch row
shards through the stages (each data replica pipelines its own rows —
PP × DP × FSDP/TP), then the LM head. Same init/step/shard_batch surface
as build_trainer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.models.llama import DecoderBlock, LlamaConfig, embed_lookup
from dlrover_tpu.parallel.pipeline import pipeline_apply
from dlrover_tpu.parallel.sharding import DEFAULT_RULES
from dlrover_tpu.trainer.train_step import TrainState

_BATCH_AXES = (MeshAxis.DATA, MeshAxis.FSDP)


def _init_llama_pipeline_params(cfg: LlamaConfig, num_stages: int,
                                rng: jax.Array, sample_seq: int):
    """Params: embed (V,H), stacked block params with leading
    (num_stages, layers_per_stage, ...), final norm + head."""
    if cfg.num_layers % num_stages:
        raise ValueError(f"{cfg.num_layers} layers not divisible by "
                         f"{num_stages} stages")
    per_stage = cfg.num_layers // num_stages
    block = DecoderBlock(cfg)
    x = jnp.zeros((1, sample_seq, cfg.hidden_size), cfg.dtype)
    positions = jnp.zeros((1, sample_seq), jnp.int32)
    rngs = jax.random.split(rng, cfg.num_layers + 2)

    def init_one(layer_rng):
        return nn.unbox(block.init(layer_rng, x, positions))["params"]

    stacked = jax.vmap(init_one)(rngs[:cfg.num_layers])
    stacked = jax.tree.map(
        lambda leaf: leaf.reshape((num_stages, per_stage)
                                  + leaf.shape[1:]), stacked)
    embed = jax.random.normal(rngs[-2],
                              (cfg.vocab_size, cfg.hidden_size),
                              cfg.param_dtype) * 0.02
    head = jax.random.normal(rngs[-1],
                             (cfg.hidden_size, cfg.vocab_size),
                             cfg.param_dtype) * 0.02
    norm = jnp.ones((cfg.hidden_size,), cfg.param_dtype)
    return {"embed": embed, "stages": stacked, "final_norm": norm,
            "lm_head": head}


def _stage_fn_factory(cfg: LlamaConfig):
    block = DecoderBlock(cfg)

    def stage_fn(stage_params, x):
        # x: (micro, seq, hidden); stage_params leaves: (per_stage, ...)
        positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])

        def one_layer(h, layer_params):
            return block.apply({"params": layer_params}, h, positions), None

        x, _ = lax.scan(one_layer, x, stage_params)
        return x

    return stage_fn


class PipelinedLlamaTrainer:
    """Same surface as ShardedTrainer (init/step/shard_batch)."""

    def __init__(self, cfg: LlamaConfig, tx: optax.GradientTransformation,
                 mesh: Mesh, num_microbatches: int, micro_batch: int,
                 seq_len: int, loss_fn, remat: bool = False,
                 rules: Optional[Sequence] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.num_stages = mesh.shape[MeshAxis.PIPE]
        self.num_microbatches = num_microbatches
        self.micro_batch = micro_batch
        self.accum_steps = num_microbatches  # microbatches play this role
        self.seq_len = seq_len
        self._tx = tx
        self._loss_fn = loss_fn
        self._remat = remat
        self._rules = list(rules if rules is not None else DEFAULT_RULES)
        # batch arrays: (M, micro, seq) with micro rows over the dp axes
        self.batch_sharding = NamedSharding(mesh, P(None, _BATCH_AXES))
        self.state_shardings = None
        self._step = None

    # -- params ---------------------------------------------------------
    def _param_shardings(self):
        """NamedSharding tree matching the params dict: stage leaves get
        P(pipe, None, *mesh-mapped logical axes) — stage-internal
        fsdp/tensor sharding composed with pipe (the reference's 3D
        topology, ds_3d_parallel_optimization.py:53)."""
        cfg = self.cfg
        block = DecoderBlock(cfg)
        x = jnp.zeros((1, self.seq_len, cfg.hidden_size), cfg.dtype)
        positions = jnp.zeros((1, self.seq_len), jnp.int32)
        from dlrover_tpu.parallel.sharding import mesh_shardings

        boxed = jax.eval_shape(
            lambda r: block.init(r, x, positions)["params"],
            jax.random.PRNGKey(0))
        block_shardings = mesh_shardings(boxed, self.mesh, self._rules)
        stage_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh,
                                    P(MeshAxis.PIPE, None, *s.spec)),
            block_shardings,
            is_leaf=lambda s: isinstance(s, NamedSharding),
        )

        def from_logical(*names):
            sh = nn.logical_to_mesh_sharding(
                P(*names), self.mesh, self._rules)
            return NamedSharding(self.mesh, sh.spec)

        return {
            "embed": from_logical("vocab", "embed"),
            "stages": stage_shardings,
            "final_norm": from_logical("norm"),
            "lm_head": from_logical("embed", "vocab"),
        }

    def init(self, rng: jax.Array) -> TrainState:
        def make_state(rng):
            params = _init_llama_pipeline_params(
                self.cfg, self.num_stages, rng, self.seq_len)
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=params,
                              opt_state=self._tx.init(params))

        abstract = jax.eval_shape(make_state, rng)
        param_shardings = self._param_shardings()
        flat_params = {
            tuple(str(getattr(k, "key", k)) for k in path): sharding
            for path, sharding in
            jax.tree_util.tree_flatten_with_path(param_shardings)[0]
        }
        replicated = NamedSharding(self.mesh, P())

        def for_path(path, leaf):
            """Optimizer moments mirror the params tree: match the longest
            path suffix against the params sharding table."""
            keys = tuple(str(getattr(k, "key", getattr(k, "name", k)))
                         for k in path)
            for start in range(len(keys)):
                if keys[start:] in flat_params:
                    sharding = flat_params[keys[start:]]
                    if len(sharding.spec) <= leaf.ndim:
                        return sharding
            return replicated

        self.state_shardings = jax.tree_util.tree_map_with_path(
            for_path, abstract)
        # jit with out_shardings: nothing ever materializes replicated
        return jax.jit(make_state,
                       out_shardings=self.state_shardings)(rng)

    # -- data -----------------------------------------------------------
    def shard_batch(self, tokens, targets):
        m, micro = self.num_microbatches, self.micro_batch
        tokens = tokens.reshape(m, micro, *tokens.shape[1:])
        targets = targets.reshape(m, micro, *targets.shape[1:])
        put = lambda x: jax.device_put(x, self.batch_sharding)
        return put(tokens), put(targets)

    # -- step -----------------------------------------------------------
    def _forward(self, params, tokens):
        cfg = self.cfg
        x = embed_lookup(params["embed"], tokens, cfg)  # (M, mb, S, H)
        out = pipeline_apply(
            self.mesh, _stage_fn_factory(cfg), params["stages"],
            x, remat=self._remat)
        from dlrover_tpu.ops.norms import reference_rms_norm

        out = reference_rms_norm(out, params["final_norm"]
                                 .astype(jnp.float32), cfg.rms_norm_eps)
        logits = jnp.dot(out.astype(cfg.dtype),
                         params["lm_head"].astype(cfg.dtype))
        return logits.astype(jnp.float32)

    def step(self, state: TrainState, tokens, targets):
        if self._step is None:
            loss_fn = self._loss_fn
            tx = self._tx

            def train_step(state, tokens, targets):
                def compute(params):
                    logits = self._forward(params, tokens)
                    return loss_fn(
                        logits.reshape(-1, *logits.shape[2:]),
                        targets.reshape(-1, *targets.shape[2:]))

                loss, grads = jax.value_and_grad(compute)(state.params)
                updates, opt_state = tx.update(grads, state.opt_state,
                                               state.params)
                params = optax.apply_updates(state.params, updates)
                return TrainState(step=state.step + 1, params=params,
                                  opt_state=opt_state), {"loss": loss}

            self._step = jax.jit(train_step, donate_argnums=(0,))
        return self._step(state, tokens, targets)


def build_pipeline_trainer(cfg: LlamaConfig,
                           tx: optax.GradientTransformation,
                           mesh: Mesh, num_microbatches: int,
                           micro_batch: int, seq_len: int, loss_fn,
                           remat: bool = False,
                           rules: Optional[Sequence] = None
                           ) -> PipelinedLlamaTrainer:
    return PipelinedLlamaTrainer(cfg, tx, mesh, num_microbatches,
                                 micro_batch, seq_len, loss_fn,
                                 remat=remat, rules=rules)
