"""Pipelined transformer trainer: circular-schedule PP over layer chunks.

Capability parity: atorch's pipeline-parallel training path (PiPPy
compile → stages → driver with GPipe/interleaved/1F1B schedules,
distributed_pippy_compiler.py:378) and the DeepSpeed 3D composition
(ds_3d_parallel_optimization.py:53 — pipe × tensor × data in one
topology); arbitrary fx-traceable models map here to any stacked-block
model via PipelineModelSpec (Llama and GPT ship built in).

TPU re-design: decoder-layer params are stacked (rounds, stages,
layers_per_chunk, ...) with the stage dim sharded over the `pipe` mesh
axis AND their trailing dims sharded over fsdp/tensor through the model's
logical axis names — the pipe shard_map is manual only over `pipe`
(jax.shard_map axis_names), so XLA keeps the stage-internal shardings and
inserts the intra-stage collectives. The embedding runs at stage 0 and
the norm + LM head + loss at the last stage INSIDE the pipeline
(parallel/pipeline.py pipeline_train), so that work is not replicated
across pipe ranks and only a scalar loss crosses stages. num_rounds > 1
gives the circular (interleaved) schedule that divides the pipeline
bubble by the round count. Same init/step/shard_batch surface as
build_trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.constants import MeshAxis
from dlrover_tpu.models.gpt import Block as GPTBlock, GPTConfig
from dlrover_tpu.models.llama import (
    DecoderBlock,
    LlamaConfig,
    embed_lookup,
)
from dlrover_tpu.parallel.pipeline import pipeline_train
from dlrover_tpu.parallel.sharding import DEFAULT_RULES
from dlrover_tpu.trainer.train_step import TrainState

_BATCH_AXES = (MeshAxis.DATA, MeshAxis.FSDP)


def _per_row(loss_fn):
    """Lift a batch-mean loss (logits, targets) -> scalar into a per-row
    vector loss (micro, seq, V), (micro, seq) -> (micro,): the pipeline
    exit must not reduce across (sharded) rows."""

    def row_losses(logits, targets):
        return jax.vmap(
            lambda lg, tg: loss_fn(lg[None], tg[None]))(logits, targets)

    return row_losses


@dataclasses.dataclass
class PipelineModelSpec:
    """Everything the pipeline needs to know about a stacked-block model.

    The reference pipelines arbitrary fx-traceable models; the analog
    here is any model expressible as enter → N identical blocks → exit.
    """

    num_layers: int
    # init ONE block's params: (rng) -> params tree (unboxed)
    init_layer: Callable[[jax.Array], Any]
    # init the shared (non-stage) params: (rng) -> dict (embedding, head…)
    init_shared: Callable[[jax.Array], Any]
    # chunk_fn(stacked_layer_params, act) -> act: run this chunk's layers
    chunk_fn: Callable[[Any, jax.Array], jax.Array]
    # enter_fn(shared, tokens_micro) -> (micro, seq, H) activation
    enter_fn: Callable[[Any, jax.Array], jax.Array]
    # exit_fn(shared, act, targets_micro) -> (micro,) per-row losses
    # (NO cross-row reduction — it runs inside a stage-divergent cond,
    # see pipeline_train)
    exit_fn: Callable[[Any, jax.Array, jax.Array], jax.Array]
    # abstract ONE-layer boxed params (for shardings): () -> boxed tree
    abstract_layer: Callable[[], Any]
    # logical specs for the shared params: dict name -> P(logical axes)
    shared_logical: Any
    # chunk_fn returns (act, aux_scalar) — MoE router losses carried to
    # the exit through the pipeline's aux accumulator
    has_aux: bool = False


# ---------------------------------------------------------------------------
# Built-in specs: Llama family and GPT (nanogpt)
# ---------------------------------------------------------------------------


def llama_pipeline_spec(cfg: LlamaConfig, seq_len: int,
                        loss_fn) -> PipelineModelSpec:
    block = DecoderBlock(cfg)
    x = jnp.zeros((1, seq_len, cfg.hidden_size), cfg.dtype)
    positions0 = jnp.zeros((1, seq_len), jnp.int32)
    # enter_fn runs once per pipeline STEP on every device (uniform
    # where-select, pipeline_train docstring): the gather lookup is
    # near-free there, the one-hot matmul is micro·seq·V·H per step.
    cfg_embed = dataclasses.replace(cfg, embed_impl="gather")

    def init_layer(rng):
        return nn.unbox(block.init(rng, x, positions0))["params"]

    def init_shared(rng):
        r_embed, r_head = jax.random.split(rng)
        return {
            "embed": jax.random.normal(
                r_embed, (cfg.vocab_size, cfg.hidden_size),
                cfg.param_dtype) * 0.02,
            "final_norm": jnp.ones((cfg.hidden_size,), cfg.param_dtype),
            "lm_head": jax.random.normal(
                r_head, (cfg.hidden_size, cfg.vocab_size),
                cfg.param_dtype) * 0.02,
        }

    def chunk_fn(stacked, h):
        positions = jnp.broadcast_to(jnp.arange(h.shape[1]), h.shape[:2])

        def one_layer(carry, layer_params):
            return block.apply({"params": layer_params}, carry,
                               positions), None

        h, _ = lax.scan(one_layer, h, stacked)
        return h

    def enter_fn(shared, tokens):
        return embed_lookup(shared["embed"], tokens, cfg_embed)

    row_losses = _per_row(loss_fn)

    def exit_fn(shared, h, targets):
        from dlrover_tpu.ops.norms import reference_rms_norm

        h = reference_rms_norm(
            h, shared["final_norm"].astype(jnp.float32), cfg.rms_norm_eps)
        logits = jnp.dot(h.astype(cfg.dtype),
                         shared["lm_head"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        return row_losses(logits, targets)

    def abstract_layer():
        return jax.eval_shape(
            lambda r: block.init(r, x, positions0)["params"],
            jax.random.PRNGKey(0))

    return PipelineModelSpec(
        num_layers=cfg.num_layers,
        init_layer=init_layer,
        init_shared=init_shared,
        chunk_fn=chunk_fn,
        enter_fn=enter_fn,
        exit_fn=exit_fn,
        abstract_layer=abstract_layer,
        shared_logical={
            "embed": ("vocab", "embed"),
            "final_norm": ("norm",),
            "lm_head": ("embed", "vocab"),
        },
    )


def llama_moe_pipeline_spec(cfg, seq_len: int,
                            loss_fn) -> PipelineModelSpec:
    """MoE decoder blocks through the pipeline (VERDICT r3 item 7; the
    reference's 3D path composes pipe with MoE,
    ds_3d_parallel_optimization.py:53 + modules/moe/moe_layer.py:161).

    The expert axis lives INSIDE each stage: expert weights carry the
    'expert' logical axis, which stays auto under the pipe-manual
    shard_map, so XLA shards experts and places the dispatch all-to-all
    per stage — pipe × expert × fsdp/tensor in one program. Router aux
    losses flow through the pipeline's aux accumulator (has_aux) and are
    folded into the objective exactly as the dense trainer's
    moe_cross_entropy_loss does. Routing is deterministic (no jitter
    rng): the per-chunk scan has no rng plumbing; use jitter_noise=0
    configs under PP (the dense trainer supports jittered gating)."""
    from dlrover_tpu.models.llama_moe import MoEDecoderBlock
    from dlrover_tpu.parallel.moe import moe_aux_loss

    block = MoEDecoderBlock(cfg, deterministic=True)
    x = jnp.zeros((1, seq_len, cfg.hidden_size), cfg.dtype)
    positions0 = jnp.zeros((1, seq_len), jnp.int32)
    dense = llama_pipeline_spec(
        dataclasses.replace(cfg, num_experts=0), seq_len, loss_fn)

    def init_layer(rng):
        return nn.unbox(block.init(rng, x, positions0))["params"]

    def chunk_fn(stacked, h):
        from dlrover_tpu.parallel.pipeline import _varying

        positions = jnp.broadcast_to(jnp.arange(h.shape[1]), h.shape[:2])

        def one_layer(carry, layer_params):
            h, aux = carry
            y, mutables = block.apply(
                {"params": layer_params}, h, positions,
                mutable=["losses"])
            return (y, aux + moe_aux_loss(mutables)), None

        # runs inside the pipe-manual shard_map: the aux carry must be
        # marked pipe-varying like the activations it will join
        aux0 = _varying(jnp.zeros((), jnp.float32), MeshAxis.PIPE)
        (h, aux), _ = lax.scan(one_layer, (h, aux0), stacked)
        return h, aux

    def abstract_layer():
        return jax.eval_shape(
            lambda r: block.init(r, x, positions0)["params"],
            jax.random.PRNGKey(0))

    return dataclasses.replace(
        dense,
        init_layer=init_layer,
        chunk_fn=chunk_fn,
        abstract_layer=abstract_layer,
        has_aux=True,
    )


def gpt_pipeline_spec(cfg: GPTConfig, seq_len: int,
                      loss_fn) -> PipelineModelSpec:
    block = GPTBlock(cfg)
    x = jnp.zeros((1, seq_len, cfg.n_embd), cfg.dtype)
    ln = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")

    def init_layer(rng):
        return nn.unbox(block.init(rng, x))["params"]

    # enter_fn runs once per pipeline STEP on every device: force the
    # cheap gather lookup (see llama_pipeline_spec).
    cfg_embed = dataclasses.replace(cfg, embed_impl="gather")

    def init_shared(rng):
        r_wte, r_wpe, r_ln = jax.random.split(rng, 3)
        return {
            "wte": jax.random.normal(
                r_wte, (cfg.vocab_size, cfg.n_embd),
                cfg.param_dtype) * 0.02,
            "wpe": jax.random.normal(
                r_wpe, (cfg.block_size, cfg.n_embd),
                cfg.param_dtype) * 0.02,
            "ln_f": nn.unbox(ln.init(r_ln, x))["params"],
        }

    def chunk_fn(stacked, h):
        def one_layer(carry, layer_params):
            return block.apply({"params": layer_params}, carry), None

        h, _ = lax.scan(one_layer, h, stacked)
        return h

    def enter_fn(shared, tokens):
        seq = tokens.shape[-1]
        return (embed_lookup(shared["wte"], tokens, cfg_embed)
                + shared["wpe"].astype(cfg.dtype)[:seq])

    row_losses = _per_row(loss_fn)

    def exit_fn(shared, h, targets):
        h = ln.apply({"params": shared["ln_f"]}, h)
        # weight-tied LM head (as nanoGPT)
        logits = jnp.dot(h, shared["wte"].astype(cfg.dtype).T)
        return row_losses(logits.astype(jnp.float32), targets)

    def abstract_layer():
        return jax.eval_shape(
            lambda r: block.init(r, x)["params"], jax.random.PRNGKey(0))

    return PipelineModelSpec(
        num_layers=cfg.n_layer,
        init_layer=init_layer,
        init_shared=init_shared,
        chunk_fn=chunk_fn,
        enter_fn=enter_fn,
        exit_fn=exit_fn,
        abstract_layer=abstract_layer,
        shared_logical={
            "wte": ("vocab", "embed"),
            "wpe": (None, "embed"),
            "ln_f": {"scale": ("norm",), "bias": ("norm",)},
        },
    )


def bert_pipeline_spec(cfg, seq_len: int, loss_fn) -> PipelineModelSpec:
    """Encoder (BERT) pipeline (VERDICT r3 item 8; reference pipelines
    arbitrary fx-traceable models, distributed_pippy_compiler.py:378).

    enter: word + position embeddings + embed LayerNorm; chunks: scanned
    EncoderBlocks (bidirectional attention); exit: MLM transform + LN +
    the weight-tied decoder over the word table + per-row loss.
    token_types ride as zeros (the segment embedding is a fine-tuning
    feature; pipeline pretraining uses single-segment packed batches)."""
    from dlrover_tpu.models.bert import BertConfig, EncoderBlock

    assert isinstance(cfg, BertConfig)
    block = EncoderBlock(cfg)
    x = jnp.zeros((1, seq_len, cfg.hidden_size), cfg.dtype)
    cfg_embed = dataclasses.replace(cfg, embed_impl="gather")
    embed_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              name="embed_norm")
    mlm_transform = nn.Dense(
        cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        name="mlm_transform")
    mlm_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="mlm_norm")

    def init_layer(rng):
        return nn.unbox(block.init(rng, x))["params"]

    def init_shared(rng):
        r_word, r_pos, r_en, r_tr, r_mn = jax.random.split(rng, 5)
        return {
            "word_embed": jax.random.normal(
                r_word, (cfg.vocab_size, cfg.hidden_size),
                cfg.param_dtype) * 0.02,
            "pos_embed": jax.random.normal(
                r_pos, (cfg.max_seq_len, cfg.hidden_size),
                cfg.param_dtype) * 0.02,
            "embed_norm": nn.unbox(embed_norm.init(r_en, x))["params"],
            "mlm_transform": nn.unbox(
                mlm_transform.init(r_tr, x))["params"],
            "mlm_norm": nn.unbox(mlm_norm.init(r_mn, x))["params"],
        }

    def chunk_fn(stacked, h):
        def one_layer(carry, layer_params):
            return block.apply({"params": layer_params}, carry), None

        h, _ = lax.scan(one_layer, h, stacked)
        return h

    def enter_fn(shared, tokens):
        seq = tokens.shape[-1]
        h = (embed_lookup(shared["word_embed"], tokens, cfg_embed)
             + shared["pos_embed"].astype(cfg.dtype)[:seq])
        return embed_norm.apply({"params": shared["embed_norm"]}, h)

    row_losses = _per_row(loss_fn)

    def exit_fn(shared, h, targets):
        h = mlm_transform.apply({"params": shared["mlm_transform"]}, h)
        h = nn.gelu(h)
        h = mlm_norm.apply({"params": shared["mlm_norm"]}, h)
        logits = jnp.dot(h, shared["word_embed"].astype(cfg.dtype).T)
        return row_losses(logits.astype(jnp.float32), targets)

    def abstract_layer():
        return jax.eval_shape(
            lambda r: block.init(r, x)["params"], jax.random.PRNGKey(0))

    return PipelineModelSpec(
        num_layers=cfg.num_layers,
        init_layer=init_layer,
        init_shared=init_shared,
        chunk_fn=chunk_fn,
        enter_fn=enter_fn,
        exit_fn=exit_fn,
        abstract_layer=abstract_layer,
        shared_logical={
            "word_embed": ("vocab", "embed"),
            "pos_embed": (None, "embed"),
            "embed_norm": {"scale": ("norm",), "bias": ("norm",)},
            "mlm_transform": {"kernel": ("embed", "mlp"),
                              "bias": ("mlp",)},
            "mlm_norm": {"scale": ("norm",), "bias": ("norm",)},
        },
    )


# ---------------------------------------------------------------------------
# Trainer
# ---------------------------------------------------------------------------


class PipelinedTrainer:
    """Same surface as ShardedTrainer (init/step/shard_batch)."""

    def __init__(self, spec: PipelineModelSpec,
                 tx: optax.GradientTransformation,
                 mesh: Mesh, num_microbatches: int, micro_batch: int,
                 seq_len: int, num_rounds: int = 1, remat: bool = False,
                 rules: Optional[Sequence] = None,
                 offload_opt_state: bool = False,
                 bound_activations: bool = False):
        self.spec = spec
        self._offload = offload_opt_state
        self.mesh = mesh
        self.num_stages = mesh.shape[MeshAxis.PIPE]
        self.num_rounds = num_rounds
        self.num_microbatches = num_microbatches
        self.micro_batch = micro_batch
        self.accum_steps = num_microbatches  # microbatches play this role
        self.seq_len = seq_len
        self._tx = tx
        self._remat = remat
        self._bound_activations = bound_activations
        self._rules = list(rules if rules is not None else DEFAULT_RULES)
        # batch arrays: (M, micro, seq) with micro rows over the dp axes
        self.batch_sharding = NamedSharding(mesh, P(None, _BATCH_AXES))
        self.state_shardings = None
        self._step = None

    @property
    def num_chunks(self) -> int:
        return self.num_stages * self.num_rounds

    @property
    def layers_per_chunk(self) -> int:
        if self.spec.num_layers % self.num_chunks:
            raise ValueError(
                f"{self.spec.num_layers} layers not divisible by "
                f"{self.num_chunks} chunks "
                f"({self.num_stages} stages × {self.num_rounds} rounds)")
        return self.spec.num_layers // self.num_chunks

    # -- params ---------------------------------------------------------
    def _param_shardings(self):
        """NamedSharding tree matching the params dict: chunk leaves get
        P(None, pipe, None, *mesh-mapped logical axes) — stage-internal
        fsdp/tensor sharding composed with pipe (the reference's 3D
        topology, ds_3d_parallel_optimization.py:53)."""
        from dlrover_tpu.parallel.sharding import mesh_shardings

        boxed = self.spec.abstract_layer()
        layer_shardings = mesh_shardings(boxed, self.mesh, self._rules)
        chunk_shardings = jax.tree.map(
            lambda s: NamedSharding(
                self.mesh, P(None, MeshAxis.PIPE, None, *s.spec)),
            layer_shardings,
            is_leaf=lambda s: isinstance(s, NamedSharding),
        )

        # Shared params (embedding / final norm / head) replicate over
        # pipe but keep their fsdp/tensor shardings: the enter/exit
        # bodies execute uniformly on every device (where-selected, see
        # pipeline_train), so their auto-axis collectives are uniform.
        def from_logical(names):
            if isinstance(names, dict):
                return {k: from_logical(v) for k, v in names.items()}
            sh = nn.logical_to_mesh_sharding(
                P(*names), self.mesh, self._rules)
            return NamedSharding(self.mesh, sh.spec)

        shared = {name: from_logical(names)
                  for name, names in self.spec.shared_logical.items()}
        return {"shared": shared, "chunks": chunk_shardings}

    def _make_params(self, rng):
        spec = self.spec
        per_chunk = self.layers_per_chunk
        r_layers, r_shared = jax.random.split(rng)
        rngs = jax.random.split(r_layers, spec.num_layers)
        stacked = jax.vmap(spec.init_layer)(rngs)
        # layer ℓ = (r·S + s)·per_chunk + j  ↔  [r, s, j] (row-major)
        stacked = jax.tree.map(
            lambda leaf: leaf.reshape(
                (self.num_rounds, self.num_stages, per_chunk)
                + leaf.shape[1:]),
            stacked)
        return {"shared": spec.init_shared(r_shared), "chunks": stacked}

    def _make_state(self, rng):
        params = self._make_params(rng)
        return TrainState(step=jnp.zeros((), jnp.int32),
                          params=params,
                          opt_state=self._tx.init(params))

    def _ensure_shardings(self, rng) -> None:
        if self.state_shardings is not None:
            return
        _ = self.layers_per_chunk   # validate divisibility eagerly
        abstract = jax.eval_shape(self._make_state, rng)
        param_shardings = self._param_shardings()
        flat_params = {
            tuple(str(getattr(k, "key", k)) for k in path): sharding
            for path, sharding in
            jax.tree_util.tree_flatten_with_path(param_shardings)[0]
        }
        replicated = NamedSharding(self.mesh, P())

        def for_path(path, leaf):
            """Optimizer moments mirror the params tree: match the longest
            path suffix against the params sharding table."""
            keys = tuple(str(getattr(k, "key", getattr(k, "name", k)))
                         for k in path)
            for start in range(len(keys)):
                if keys[start:] in flat_params:
                    sharding = flat_params[keys[start:]]
                    if len(sharding.spec) <= leaf.ndim:
                        return sharding
            return replicated

        self.state_shardings = jax.tree_util.tree_map_with_path(
            for_path, abstract)
        if self._offload:
            from dlrover_tpu.common.jax_compat import host_memory_kind

            # optimizer moments live in HOST memory (same mechanism as
            # build_trainer's offload_opt_state: pinned_host memory kind
            # on the shardings; XLA inserts the host↔HBM transfers
            # around the update). Scalars stay on device — the SPMD
            # partitioner rejects memory kinds on them.
            host_kind = host_memory_kind(self.mesh.devices.flat[0])
            self.state_shardings = self.state_shardings.replace(
                opt_state=jax.tree.map(
                    lambda s, a: s if a.ndim == 0 else NamedSharding(
                        self.mesh, s.spec, memory_kind=host_kind),
                    self.state_shardings.opt_state, abstract.opt_state,
                ))

    def abstract_state(self, rng: jax.Array) -> TrainState:
        """Abstract TrainState (shapes + shardings) — the checkpoint
        restore target, same surface as ShardedTrainer."""
        from dlrover_tpu.trainer.train_step import (
            abstract_state_with_shardings,
        )

        self._ensure_shardings(rng)
        return abstract_state_with_shardings(
            jax.eval_shape(self._make_state, rng), self.state_shardings)

    def init(self, rng: jax.Array) -> TrainState:
        self._ensure_shardings(rng)
        # jit with out_shardings: nothing ever materializes replicated
        return jax.jit(self._make_state,
                       out_shardings=self.state_shardings)(rng)

    # -- data -----------------------------------------------------------
    def shard_batch(self, tokens, targets):
        m, micro = self.num_microbatches, self.micro_batch
        tokens = tokens.reshape(m, micro, *tokens.shape[1:])
        targets = targets.reshape(m, micro, *targets.shape[1:])
        put = lambda x: jax.device_put(x, self.batch_sharding)
        return put(tokens), put(targets)

    # -- step -----------------------------------------------------------
    def _loss(self, params, tokens, targets):
        spec = self.spec
        return pipeline_train(
            self.mesh, spec.chunk_fn, params["chunks"], params["shared"],
            spec.enter_fn, spec.exit_fn, tokens, targets,
            num_rounds=self.num_rounds, remat=self._remat,
            chunk_has_aux=spec.has_aux,
            # 1F1B-style bound: one checkpointed window of num_stages
            # schedule steps live at a time (see pipeline_train)
            activation_groups=(self.num_stages
                               if self._bound_activations else 0))

    def step(self, state: TrainState, tokens, targets):
        if self._step is None:
            tx = self._tx

            def train_step(state, tokens, targets):
                loss, grads = jax.value_and_grad(self._loss)(
                    state.params, tokens, targets)
                updates, opt_state = tx.update(grads, state.opt_state,
                                               state.params)
                params = optax.apply_updates(state.params, updates)
                return TrainState(step=state.step + 1, params=params,
                                  opt_state=opt_state), {"loss": loss}

            self._step = jax.jit(train_step, donate_argnums=(0,))
        return self._step(state, tokens, targets)


def build_pipeline_trainer(cfg: Union[LlamaConfig, GPTConfig],
                           tx: optax.GradientTransformation,
                           mesh: Mesh, num_microbatches: int,
                           micro_batch: int, seq_len: int, loss_fn,
                           num_rounds: int = 1,
                           remat: bool = False,
                           rules: Optional[Sequence] = None,
                           offload_opt_state: bool = False,
                           bound_activations: bool = False
                           ) -> PipelinedTrainer:
    """Lower a stacked-block model config to a pipelined trainer.

    Any model family with a PipelineModelSpec pipelines; LlamaConfig and
    GPTConfig ship built in (the reference pipelines arbitrary
    fx-traceable models via PiPPy — spec construction is the analog).

    loss_fn contract: a BATCH-MEAN loss (logits, targets) -> scalar, the
    mean over its batch rows (cross_entropy_loss qualifies). The pipeline
    applies it per microbatch row and averages — a sum-reducing loss
    would silently change scale vs the dense trainer."""
    # bf16 pipelines compile everywhere: the XLA-CPU half-precision
    # collective bug is dodged surgically inside pipeline_train (shared
    # params cross the shard_map boundary in fp32 on CPU — pvary'd
    # BEFORE the compute-dtype cast — so their grad psum, the
    # instruction the CPU compiler CHECK-failed on, runs fp32 while
    # every stage computes in the real dtype). One residue: MoE chunks
    # under PP put the expert axis auto INSIDE the pipe-manual region,
    # and GSPMD inserts bf16 expert collectives there that the same CPU
    # promotion pass chokes on — those configs force fp32 on CPU only.
    from dlrover_tpu.models.llama_moe import LlamaMoEConfig

    if (jax.default_backend() == "cpu"
            and isinstance(cfg, LlamaMoEConfig)
            and getattr(cfg, "num_experts", 0) > 0
            and jnp.dtype(cfg.dtype) in (jnp.bfloat16, jnp.float16)):
        from dlrover_tpu.common.log import default_logger as logger

        logger.info("MoE pipeline: forcing fp32 on the cpu backend "
                    "(GSPMD-inserted half-precision expert collectives "
                    "inside the pipe-manual region hit the XLA-CPU "
                    "promotion bug); dense pipelines stay bf16")
        replace = {"dtype": jnp.float32}
        if jnp.dtype(cfg.param_dtype) in (jnp.bfloat16, jnp.float16):
            replace["param_dtype"] = jnp.float32
        cfg = dataclasses.replace(cfg, **replace)

    if isinstance(cfg, LlamaMoEConfig):
        # (checked before LlamaConfig — LlamaMoEConfig subclasses it;
        # without this order an MoE config would pipeline as dense)
        spec = llama_moe_pipeline_spec(cfg, seq_len, loss_fn)
    elif isinstance(cfg, LlamaConfig):
        spec = llama_pipeline_spec(cfg, seq_len, loss_fn)
    elif isinstance(cfg, GPTConfig):
        spec = gpt_pipeline_spec(cfg, seq_len, loss_fn)
    else:
        from dlrover_tpu.models.bert import BertConfig

        if isinstance(cfg, BertConfig):
            spec = bert_pipeline_spec(cfg, seq_len, loss_fn)
        else:
            raise NotImplementedError(
                f"no pipeline spec for {type(cfg).__name__}; provide a "
                "PipelineModelSpec and construct PipelinedTrainer "
                "directly")
    return PipelinedTrainer(spec, tx, mesh, num_microbatches,
                            micro_batch, seq_len, num_rounds=num_rounds,
                            remat=remat, rules=rules,
                            offload_opt_state=offload_opt_state,
                            bound_activations=bound_activations)
